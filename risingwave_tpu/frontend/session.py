"""Frontend session: the run-one-query loop + streaming-job deployment.

Reference parity: src/utils/pgwire/src/pg_server.rs:53
(`Session::run_one_query`), src/frontend/src/handler/ (per-statement
handlers) and the meta-side DdlController + GlobalStreamManager
(create job → build actors → activate via barrier) — collapsed into
one in-process object for the single-node deployment shape. The
barrier loop is the session's heartbeat; FLUSH forces a checkpoint
(handler/flush.rs analog).
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Union

from risingwave_tpu.frontend import ast
from risingwave_tpu.frontend.catalog import Catalog, MvCatalog
from risingwave_tpu.frontend.planner import (
    PlanError, StreamPlanner, plan_batch, source_schema,
)
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.state.store import MemoryStateStore, StateStore
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.message import (
    PauseMutation, ResumeMutation, StopMutation,
)

Rows = List[tuple]


class Frontend:
    """One session over one in-process cluster.

    If the state store is object-store-backed (HummockLite), the DDL
    log persists at meta/ddl.json — the MetaStore analog. A fresh
    Frontend over the same objects replays it on boot: the catalog
    rebuilds, every MV's pipeline redeploys, and state/offsets resume
    from the committed epoch (recovery.rs semantics, collapsed to DDL
    replay + StateTable recovery)."""

    def __init__(self, store: Optional[StateStore] = None,
                 rate_limit: Optional[int] = 8,
                 min_chunks: Optional[int] = None,
                 parallelism: int = 1,
                 join_state_cap: Optional[int] = None,
                 epoch_pipeline: bool = True):
        self.store = store if store is not None else MemoryStateStore()
        # parallelism > 1: GROUP BY plans run on the vnode-sharded SPMD
        # kernel over a device mesh (the fragmenter's hash-exchange
        # parallelism, §2.12, as one all_to_all program)
        self.mesh = self._mesh_for(parallelism)
        self.catalog = Catalog()
        self.local = LocalBarrierManager()
        # pipelined epochs (ISSUE 13): with epoch_pipeline on (the
        # default, SET stream_epoch_pipeline = off to opt out), a
        # BarrierPlane partitions deployed jobs into alignment domains
        # — each domain's barriers flow independently, checkpoints stay
        # cross-domain aligned on their own cadence. Off reproduces the
        # single global BarrierLoop bit-identically (the oracle arm).
        self._epoch_pipeline = bool(epoch_pipeline)
        self._plane = None
        self._legacy_loop = None
        # exactly-once sinks (ISSUE 20): ONE coordinator per frontend
        # (= per barrier engine) — its commit authority is THIS
        # engine's checkpoint floor, so two frontends in one process
        # (oracle arm beside arm under test) never cross-commit
        from risingwave_tpu.meta.sink_coordinator import SinkCoordinator
        self.sinks = SinkCoordinator()
        self._rebuild_barrier_engine()
        self.actors: Dict[int, Actor] = {}
        self.tasks: Dict[int, asyncio.Task] = {}
        self.readers: Dict[str, Dict[int, object]] = {}   # mv → readers
        self.rate_limit = rate_limit
        self.min_chunks = min_chunks
        # resident join-state cap (cold-tier eviction; None = unbounded)
        self.join_state_cap = join_state_cap
        # unified state-tiering cap (state/tier.py): resident-KEY cap
        # per stateful executor cache — agg groups, join sides, TopN
        # group caches. None/0 = unbounded. Recorded per MV at CREATE
        # (the cap shapes join state-table pks) and replayed at
        # reschedule, like _mv_rules.
        self.state_tier_cap: Optional[int] = None
        self._mv_tier_caps: Dict[str, Optional[int]] = {}
        # adaptive chunk coalescing in front of keyed executors
        # (stream/coalesce.py): target cardinality per device dispatch
        # (0 disables) and the linger bound in buffered chunks
        from risingwave_tpu.stream.coalesce import (
            DEFAULT_MAX_CHUNKS, DEFAULT_TARGET_ROWS,
        )
        self.chunk_target_rows = DEFAULT_TARGET_ROWS
        self.coalesce_linger_chunks = DEFAULT_MAX_CHUNKS
        # session configuration (src/common/src/session_config/
        # analog): typed knobs bind to REAL planner inputs, the rest
        # are pg-compatibility strings (shared impl: session_vars.py)
        from risingwave_tpu.frontend.opt import parse_fusion, parse_rules
        from risingwave_tpu.frontend.session_vars import SessionVars
        from risingwave_tpu.stream.costs import (
            parse_costs as _parse_costs,
        )
        from risingwave_tpu.stream.monitor import (
            parse_tricolor as _parse_tricolor,
        )
        from risingwave_tpu.utils.ledger import parse_ledger
        from risingwave_tpu.utils.spans import parse_trace
        self.session_vars = SessionVars(
            self, {"streaming_rate_limit": "rate_limit",
                   "streaming_min_chunks": "min_chunks",
                   "join_state_cap": "join_state_cap",
                   "state_tier_cap": "state_tier_cap",
                   "state_tier_soft_limit_mb":
                       "state_tier_soft_limit_mb",
                   "stream_chunk_target_rows": "chunk_target_rows",
                   # decoupled checkpoint cadence (ISSUE 13): durable
                   # checkpoints every k-th round; plain barriers
                   # advance per-domain in between
                   "stream_checkpoint_frequency":
                       "checkpoint_frequency",
                   "stream_coalesce_linger_chunks":
                       "coalesce_linger_chunks"},
            {"application_name": "", "timezone": "UTC",
             # plan-rewrite toggles (frontend/opt): 'all' | 'none' |
             # comma-list of rule names, validated at SET time
             "stream_rewrite_rules": "all",
             # fragment fusion (opt/fusion.py): compile each
             # fragment's filter/project run into the keyed kernel's
             # jitted step (one dispatch, donated state); 'off'
             # restores the interpretive chain
             "stream_fusion": "on",
             # epoch-causal tracing (utils/spans.py): always-on
             # bounded flight recorder; 'off' reduces every hook to a
             # predicate check (and keeps remote barrier frames free
             # of the span-context trailer)
             "stream_trace": "on",
             # epoch phase ledger (utils/ledger.py): per-epoch
             # host/device time-and-bytes accounting with the
             # conservation gate; 'off' reduces every hook to a
             # predicate check (the ledger-on-vs-off bench arm)
             "stream_ledger": "on",
             # barrier domains (meta/domains.py): 'off' restores one
             # global BarrierLoop — today's lockstep, bit-identical
             # (the oracle arm). Only changeable with no live jobs.
             "stream_epoch_pipeline":
                 "on" if self._epoch_pipeline else "off",
             # freshness & bottleneck attribution (ISSUE 14): the
             # utilization tricolor, per-MV freshness sampling and
             # the bottleneck walker; 'off' reduces every hook to a
             # predicate check (the q7_tricolor_off bench arm)
             "stream_tricolor": "on",
             # cost & skew attribution (ISSUE 16): per-MV resource
             # ledger, state topology upkeep and hot-key sketches;
             # 'off' reduces every hook to a predicate check (the
             # q7_costs_off bench arm)
             "stream_costs": "on",
             # compaction arm (ISSUE 19): 'inline' compacts on the
             # commit path (oracle arm); 'dedicated' moves every merge
             # off-path through the CompactionManager + a background
             # compactor — zero compact() frames on the barrier path
             "storage_compaction": "inline"},
            validators={"stream_rewrite_rules": parse_rules,
                        "stream_fusion": parse_fusion,
                        "stream_trace": parse_trace,
                        "stream_ledger": parse_ledger,
                        "stream_tricolor": _parse_tricolor,
                        "stream_costs": _parse_costs,
                        "storage_compaction":
                            self._validate_compaction,
                        "stream_epoch_pipeline":
                            self._validate_epoch_pipeline})
        # rules spec each MV was created under: reschedule replans +
        # re-rewrites with the SAME spec so state-table schemas from
        # the original rewrite reproduce exactly (id-base contract)
        self._mv_rules: Dict[str, str] = {}
        # fusion setting each MV was created under — reschedule
        # re-fuses (or not) exactly as the CREATE did
        self._mv_fusion: Dict[str, bool] = {}
        self._next_actor = 1000
        self.chain_edges: Dict[str, list] = {}   # job → [(uid, Output)]
        # name → CREATE MV select AST (reschedule replans from this —
        # the DDL log may hold stale same-name CREATEs after drops)
        self._mv_selects: Dict[str, object] = {}
        # catalog-change broadcast (meta notification service analog):
        # observers get a snapshot then versioned deltas
        from risingwave_tpu.meta.notification import NotificationService
        self.notifications = NotificationService(
            snapshot_fn=self._catalog_snapshot)
        self._ddl_log: List[str] = []
        self._replaying = False
        # table name → (DmlReader, schema, pk, RowIdSeq|None, tid):
        # the DML write path into each CREATE TABLE job
        self._tables: Dict[str, tuple] = {}
        # serializes barrier rounds between DDL handlers, step() and the
        # background heartbeat (inject_and_collect is not reentrant)
        self._barrier_lock = asyncio.Lock()
        # dedicated-compaction arm (SET storage_compaction): the
        # manager ticks after each barrier round; merges run on the
        # InProcessCompactor's background thread
        self._compaction_mgr = None
        self._compactor = None

    # -- barrier engine (ISSUE 13) ---------------------------------------
    def _rebuild_barrier_engine(self) -> None:
        """Swap between the domain plane and the legacy global loop
        (only legal with no live jobs — the SET validator enforces)."""
        freq = self.checkpoint_frequency if (
            self._plane is not None or self._legacy_loop is not None) \
            else 1
        if self._epoch_pipeline:
            from risingwave_tpu.meta.domains import BarrierPlane
            self._plane = BarrierPlane(self.local, self.store,
                                       checkpoint_frequency=freq)
            self._legacy_loop = None
        else:
            self._legacy_loop = BarrierLoop(self.local, self.store,
                                            checkpoint_frequency=freq)
            self._plane = None
        # sink staging/commit ride the engine's checkpoint pipeline:
        # stage before the floor's durable commit, manifest after it
        self.loop.uploader.sinks = self.sinks

    @property
    def loop(self):
        """The barrier engine: a BarrierPlane (domains) or a single
        BarrierLoop (off arm) — same driving surface either way."""
        return self._plane if self._plane is not None \
            else self._legacy_loop

    @property
    def checkpoint_frequency(self) -> int:
        """SET stream_checkpoint_frequency: durable checkpoints land
        every k-th barrier round (aligned across domains); plain
        rounds advance per-domain. 1 = every round (the historical
        default)."""
        eng = self.loop
        return eng.checkpoint_frequency if eng is not None else 1

    @checkpoint_frequency.setter
    def checkpoint_frequency(self, v) -> None:
        eng = self.loop
        if eng is not None:
            eng.checkpoint_frequency = max(1, int(v))

    def _validate_epoch_pipeline(self, spec: str) -> bool:
        from risingwave_tpu.meta.domains import parse_epoch_pipeline
        want = parse_epoch_pipeline(spec)
        if want != self._epoch_pipeline and self.actors:
            raise PlanError(
                "stream_epoch_pipeline cannot change with live jobs — "
                "drop them first")
        return want

    # -- dedicated compaction (ISSUE 19) ---------------------------------
    def _validate_compaction(self, spec: str) -> str:
        from risingwave_tpu.meta.compaction import parse_compaction
        mode = parse_compaction(spec)
        if mode == "dedicated" and not hasattr(self.store,
                                               "level_snapshot"):
            raise PlanError(
                "storage_compaction='dedicated' requires an object-"
                "store-backed state store (HummockLite)")
        return mode

    async def _set_compaction_mode(self, mode: str) -> None:
        """Flip the arm at runtime. Dedicated wires the store into a
        CompactionManager over an InProcessCompactor (ONE background
        merge thread); inline tears both down — the L0 backlog then
        drains at the next commit trigger."""
        if not hasattr(self.store, "compaction_mode"):
            return                       # memory store: inline only
        if mode == self.store.compaction_mode:
            return
        self.store.compaction_mode = mode
        if mode == "dedicated":
            from risingwave_tpu.meta.compaction import (
                CompactionManager, CompactorHooks,
            )
            from risingwave_tpu.storage.compactor import (
                InProcessCompactor,
            )
            self._compactor = InProcessCompactor(self.store.obj)
            self._compaction_mgr = CompactionManager()
            self._compaction_mgr.add_namespace("local", CompactorHooks(
                snapshot=self.store.level_snapshot,
                reserve=self.store.reserve_task,
                apply=self.store.apply_version_delta,
                abort=self.store.abort_task,
                execute=self._compactor.submit))
        else:
            mgr, self._compaction_mgr = self._compaction_mgr, None
            comp, self._compactor = self._compactor, None
            if mgr is not None:
                await mgr.drain()    # land a finished merge, don't leak it
            if comp is not None:
                comp.close()

    # -- state-tier pressure knob (SET state_tier_soft_limit_mb) ---------
    @property
    def state_tier_soft_limit_mb(self) -> int:
        """Pressure watermark for the state tier: the MemoryContext
        soft limit (utils/memory.py) in MB; 0 = unlimited. Process-
        global — the checkpoint tick sweeps ONE context per process."""
        from risingwave_tpu.utils import memory as _mem
        sl = _mem.GLOBAL.soft_limit
        return 0 if sl is None else int(sl) >> 20

    @state_tier_soft_limit_mb.setter
    def state_tier_soft_limit_mb(self, v) -> None:
        from risingwave_tpu.utils import memory as _mem
        _mem.GLOBAL.soft_limit = None if not v else int(v) << 20

    # -- DDL-log durability (MetaStore analog) ---------------------------
    @property
    def _meta_obj(self):
        return getattr(self.store, "obj", None)

    def _persist_ddl(self) -> None:
        if self._meta_obj is not None and not self._replaying:
            import json
            self._meta_obj.upload(
                "meta/ddl.json", json.dumps(self._ddl_log).encode())

    async def recover(self) -> int:
        """Replay the persisted DDL log (boot path). Returns #stmts."""
        if self._meta_obj is None or not self._meta_obj.exists(
                "meta/ddl.json"):
            return 0
        import json
        log = json.loads(self._meta_obj.read("meta/ddl.json").decode())
        # restore the durable history FIRST — the next DDL statement
        # re-persists the whole log, so losing it here would truncate
        # the catalog on the following recovery
        self._ddl_log = list(log)
        # the previous generation is dead (single-writer recovery):
        # clear its crash residue — uploaded-but-uncommitted SSTs no
        # version references would otherwise accumulate forever across
        # kill/recover generations
        if hasattr(self.store, "vacuum_orphans"):
            self.store.vacuum_orphans()
        self._replaying = True
        try:
            for sql in log:
                await self.execute(sql)
        finally:
            self._replaying = False
        if self.actors:
            await self._barrier(mutation=ResumeMutation())
        return len(log)

    # -- public API -------------------------------------------------------
    async def execute(self, sql: str) -> Union[Rows, str]:
        """Run one or more ';'-separated statements; returns the last
        statement's result (rows for SELECT/SHOW, status otherwise)."""
        from risingwave_tpu.frontend.parser import parse_many

        result: Union[Rows, str] = "OK"
        for text, stmt in parse_many(sql):
            result = await self._run(stmt)
            if isinstance(stmt, ast.SetVar) and \
                    stmt.name in ("stream_rewrite_rules",
                                  "stream_fusion",
                                  "stream_trace",
                                  "state_tier_cap",
                                  "state_tier_soft_limit_mb") and \
                    not self._replaying:
                # these SETs shape what CREATE produces — the rewrite
                # spec shapes STATE-TABLE schemas (pruned joins persist
                # narrowed rows) and the tier cap shapes join
                # state-table pks (key-prefixed for prefix-scan
                # reload); recovery must replay CREATEs under the same
                # values, so the SET itself rides the DDL log
                self._ddl_log.append(text)
                self._persist_ddl()
            if isinstance(stmt, (ast.CreateSource,
                                 ast.CreateMaterializedView,
                                 ast.CreateSink, ast.DropSink,
                                 ast.DropMaterializedView,
                                 ast.DropSource, ast.CreateTable,
                                 ast.DropTable,
                                 ast.AlterParallelism)) and \
                    not self._replaying:
                # replayed DDL publishes nothing: observers' snapshots
                # already contain the replayed catalog
                from risingwave_tpu.meta.notification import (
                    Notification,
                )
                self._ddl_log.append(text)
                self._persist_ddl()
                self.notifications.publish(Notification(
                    type(stmt).__name__, {
                        "name": getattr(stmt, "name", None),
                        "version_hint": len(self._ddl_log)}))
        return result

    def execute_sync(self, sql: str) -> Union[Rows, str]:
        return asyncio.get_event_loop().run_until_complete(
            self.execute(sql))

    async def _barrier(self, **kw):
        """One serialized barrier round — the ONLY way any session code
        may call inject_and_collect (the lock also guards actor-topology
        mutations; see _create_mv/_drop_mv)."""
        async with self._barrier_lock:
            r = await self.loop.inject_and_collect(**kw)
        if self._compaction_mgr is not None:
            # dedicated arm: the manager settles finished merges
            # (cheap manifest swaps) and dispatches new ones to the
            # background thread — no compact() frame ever runs here
            await self._compaction_mgr.tick()
        return r

    async def step(self, n: int = 1) -> None:
        """Drive n checkpoint barriers (deterministic test/bench mode)."""
        for _ in range(n):
            await self._barrier(force_checkpoint=True)

    async def run_heartbeat(self, interval_s: float = 0.25) -> None:
        """Background barrier heartbeat for server deployments
        (GlobalBarrierManager::run analog; serialized with DDL). A
        failure is loud: it propagates out of this task — the server
        entry point watches it and dies rather than serving a cluster
        whose checkpoints silently stopped."""
        import sys
        import traceback
        try:
            while True:
                await asyncio.sleep(interval_s)
                # no uploader drain: the heartbeat is exactly the
                # driver the async checkpoint pipeline overlaps —
                # draining every beat would stall barrier cadence on
                # object-store latency again. Failures still surface
                # on the next beat's collect; FLUSH/DDL/step() keep
                # their durable (draining) semantics.
                await self._barrier(drain_uploader=False)
        except asyncio.CancelledError:
            pass
        except BaseException:
            print("barrier heartbeat failed:", file=sys.stderr)
            traceback.print_exc()
            raise

    async def close(self) -> None:
        if self._compactor is not None:
            mgr, self._compaction_mgr = self._compaction_mgr, None
            comp, self._compactor = self._compactor, None
            if mgr is not None:
                await mgr.drain()
            comp.close()
        if self.actors:
            async with self._barrier_lock:
                stop_ids = set(self.actors)
                for readers in self.readers.values():
                    stop_ids |= set(readers)
                await self.loop.inject_and_collect(
                    mutation=StopMutation(frozenset(stop_ids)))
                for t in self.tasks.values():
                    await t
        for aid, a in self.actors.items():
            if a.failure is not None:
                raise a.failure

    # -- dispatch ---------------------------------------------------------
    async def _run(self, stmt) -> Union[Rows, str]:
        self.last_select_schema = None
        if isinstance(stmt, ast.CreateSource):
            schema = source_schema(stmt.options, stmt.columns)
            self.catalog.add_source(stmt.name, schema, stmt.options)
            return "CREATE_SOURCE"
        if isinstance(stmt, ast.CreateMaterializedView):
            return await self._create_mv(stmt)
        if isinstance(stmt, ast.AlterParallelism):
            return await self._alter_parallelism(stmt)
        if isinstance(stmt, ast.Explain):
            return self._explain(stmt.select)
        if isinstance(stmt, ast.CreateSink):
            return await self._create_sink(stmt)
        if isinstance(stmt, ast.DropSink):
            return await self._drop_job(
                stmt.name, self.catalog.sinks, stmt.if_exists,
                "DROP_SINK")
        if isinstance(stmt, ast.DropMaterializedView):
            return await self._drop_mv(stmt)
        if isinstance(stmt, ast.DropSource):
            if stmt.name not in self.catalog.sources:
                if stmt.if_exists:
                    return "DROP_SOURCE"
                raise PlanError(f"unknown source {stmt.name!r}")
            dependents = (list(self.catalog.mvs.values())
                          + list(self.catalog.sinks.values()))
            for job in dependents:
                if stmt.name in job.dependent_sources:
                    raise PlanError(
                        f"source {stmt.name!r} is used by {job.name!r}")
            del self.catalog.sources[stmt.name]
            return "DROP_SOURCE"
        if isinstance(stmt, ast.CreateTable):
            return await self._create_table(stmt)
        if isinstance(stmt, ast.DropTable):
            return await self._drop_table(stmt)
        if isinstance(stmt, ast.Insert):
            return await self._insert(stmt)
        if isinstance(stmt, ast.Delete):
            return await self._delete(stmt)
        if isinstance(stmt, ast.Update):
            return await self._update(stmt)
        if isinstance(stmt, ast.SetVar):
            self.session_vars.set(stmt.name, stmt.value)
            if stmt.name == "stream_trace":
                # runtime toggle, not a CREATE-time knob: flips the
                # process-global tracer right away (TO DEFAULT → on)
                from risingwave_tpu.utils import spans as _spans
                _spans.set_enabled(_spans.parse_trace(
                    self.session_vars.get("stream_trace")))
            if stmt.name == "stream_ledger":
                from risingwave_tpu.utils import ledger as _ledger
                _ledger.set_enabled(_ledger.parse_ledger(
                    self.session_vars.get("stream_ledger")))
            if stmt.name == "stream_tricolor":
                # one knob for the whole attribution subsystem: the
                # tricolor bookkeeping AND freshness sampling flip
                # together (the bench off-arm measures both)
                from risingwave_tpu.stream import freshness as _fresh
                from risingwave_tpu.stream import monitor as _monitor
                on = _monitor.parse_tricolor(
                    self.session_vars.get("stream_tricolor"))
                _monitor.set_tricolor(on)
                _fresh.set_enabled(on)
            if stmt.name == "stream_costs":
                # flips the per-MV cost rollup, topology upkeep and
                # hot-key sketches together (stream/costs.py owns the
                # fan-out to its sibling flags)
                from risingwave_tpu.stream import costs as _mvcosts
                _mvcosts.set_enabled(_mvcosts.parse_costs(
                    self.session_vars.get("stream_costs")))
            if stmt.name == "storage_compaction":
                # runtime arm flip (validated above): wires/tears the
                # dedicated compactor — never rides the DDL log
                await self._set_compaction_mode(
                    self.session_vars.get("storage_compaction"))
            if stmt.name == "stream_epoch_pipeline":
                from risingwave_tpu.meta.domains import (
                    parse_epoch_pipeline,
                )
                want = parse_epoch_pipeline(
                    self.session_vars.get("stream_epoch_pipeline"))
                if want != self._epoch_pipeline:
                    # validator already refused with live jobs
                    self._epoch_pipeline = want
                    self._rebuild_barrier_engine()
            return "SET"
        if isinstance(stmt, ast.Show):
            if stmt.what == "var:all":
                return self.session_vars.show_all()
            if stmt.what.startswith("var:"):
                name = stmt.what[4:].lower()
                if not self.session_vars.known(name):
                    raise PlanError(
                        f"unrecognized configuration parameter "
                        f"{name!r}")
                return [(self.session_vars.get(name),)]
            if stmt.what == "sources":
                return [(n,) for n in sorted(self.catalog.sources)]
            if stmt.what == "sinks":
                return [(n,) for n in sorted(self.catalog.sinks)]
            if stmt.what == "tables":
                return [(n,) for n in sorted(self._tables)]
            return [(n,) for n in sorted(self.catalog.mvs)
                    if n not in self._tables]
        if isinstance(stmt, ast.Flush):
            await self._barrier(force_checkpoint=True)
            return "FLUSH"
        if isinstance(stmt, ast.Select):
            return await self._select(stmt)
        raise PlanError(f"unhandled statement {stmt!r}")

    # -- handlers ---------------------------------------------------------
    def _freshness_sources(self, deps) -> list:
        """Resolve a job's dependency anchors to the SOURCE names whose
        ingest frontiers bound its freshness (MV-on-MV deps resolve
        transitively — chained materializations preserve the barrier
        cut, so the original source frontier is still the honest
        visible-data bound)."""
        out, seen = [], set()

        def walk(d):
            if d in seen:
                return
            seen.add(d)
            if d in self.catalog.sources or d in self._tables:
                out.append(d)
            elif d in self.catalog.mvs:
                for dd in self.catalog.mvs[d].dependent_sources:
                    walk(dd)

        for d in deps:
            walk(d)
        return out

    async def _deploy_job(self, name: str, actor_id: int, consumer,
                          readers, register, attaches=(),
                          deps=(), freshness_sources=None) -> None:
        """Shared deployment tail for MVs and sinks — runs UNDER the
        barrier lock the caller holds: topology mutations (sender
        registration in plan(), expected-actor set, spawn) racing a
        heartbeat epoch would leave it collecting against actors that
        never received it. ``deps`` (source/MV names the job reads)
        are the job's barrier-domain reachability anchors: jobs that
        share a dep — a source fan-out, an MV-on-MV chain, a temporal
        dim read — align in one domain; disjoint jobs get their own."""
        register()                    # catalog entry (duplicate check)
        # every deployed chain is instrumented node-by-node: row/chunk
        # throughput and exclusive processing time per (fragment,
        # actor, executor), feeding rw_actor_metrics + the profiler
        from risingwave_tpu.stream.monitor import install_monitoring
        consumer = install_monitoring(consumer, fragment=name,
                                      actor_id=actor_id)
        # every MV actor carries an (initially empty) broadcast
        # dispatcher so later MV-on-MV chains can attach outputs at a
        # barrier boundary (Mutation::Add analog)
        from risingwave_tpu.stream.dispatch import BroadcastDispatcher
        actor = Actor(actor_id, consumer,
                      dispatchers=[BroadcastDispatcher([])],
                      barrier_manager=self.local, fragment=name)
        self.actors[actor_id] = actor
        self.readers[name] = readers
        self.local.set_expected_actors(list(self.actors))
        self.tasks[actor_id] = actor.spawn()
        if self._plane is not None:
            # domain derivation BEFORE the activation barrier: the new
            # job's first barrier must already flow through its domain
            self._plane.assign_job(name, set(deps),
                                   sender_ids=set(readers),
                                   expected_ids={actor_id})
        # freshness lineage (stream/freshness.py): which source
        # frontiers bound this job's visible data, keyed by the domain
        # its barriers flow through
        from risingwave_tpu.stream.freshness import FRESHNESS
        domain = ""
        if self._plane is not None:
            domain = self._plane.domain_of_job(name) or ""
        FRESHNESS.register_mv(
            name,
            self._freshness_sources(deps)
            if freshness_sources is None else list(freshness_sources),
            domain)
        if self._plane is not None:
            # a new job can MERGE domains (shared reachability): keep
            # every registered job's freshness domain key current
            for dom in self._plane.domains():
                for job in self._plane.jobs_of_domain(dom):
                    FRESHNESS.set_domain(job, dom)
        # attach MV-on-MV chain edges now that the plan validated and
        # the downstream actor exists — the activation barrier below
        # must flow through these channels
        self.chain_edges[name] = list(attaches)
        for uid, out in attaches:
            d = self.actors[uid].dispatchers[0]
            d.update_outputs(d.outputs() + [out])
        # activation barrier (Command::CreateStreamingJob analog).
        # During DDL replay, sources stay PAUSED so no upstream data
        # flows before every downstream chain has re-attached — a
        # revived MV-on-MV chain with completed backfill would miss
        # deltas emitted in earlier replayed jobs' activation epochs
        # (recovery.rs: rebuild paused, resume at the end).
        mutation = PauseMutation() if self._replaying else None
        await self.loop.inject_and_collect(force_checkpoint=True,
                                           mutation=mutation)
        self._deployed_actor = actor

    def _explain(self, sel: ast.Select) -> Rows:
        """EXPLAIN <select>: the streaming plan as indented text —
        BOTH the planner's tree and the rewritten tree, with per-rule
        annotations and carried-lane stats in between. Plans against a
        throwaway barrier manager so no senders or channels leak from
        a statement that deploys nothing."""
        from risingwave_tpu.frontend.planner import explain_tree
        planner = StreamPlanner(self.catalog, self.store,
                                LocalBarrierManager(), definition="",
                                mesh=self.mesh, actors=self.actors,
                                chunk_target_rows=self.chunk_target_rows,
                                coalesce_linger_chunks=self
                                .coalesce_linger_chunks)
        plan = planner.plan("__explain__", sel, actor_id=0,
                            rate_limit=self.rate_limit,
                            min_chunks=self.min_chunks)
        from risingwave_tpu.frontend.opt import (
            explain_with_rewrite, parse_fusion,
        )
        rules = self.session_vars.get("stream_rewrite_rules")
        return explain_with_rewrite(
            plan.consumer, rules,
            fusion=parse_fusion(self.session_vars.get("stream_fusion")))

    def _catalog_snapshot(self) -> list:
        """Current catalog as notification payloads (observers get
        this before any live delta — snapshot-then-delta contract)."""
        out = []
        for s in self.catalog.sources.values():
            out.append({"kind": "source", "name": s.name})
        for m in self.catalog.mvs.values():
            out.append({"kind": "mv", "name": m.name,
                        "table_id": m.table_id})
        for sk in self.catalog.sinks.values():
            out.append({"kind": "sink", "name": sk.name})
        return out

    @staticmethod
    def _mesh_for(parallelism: int):
        """n-device mesh for a parallel plan (None = single-chip)."""
        if parallelism <= 1:
            return None
        import jax
        from jax.sharding import Mesh

        import numpy as _np
        devs = jax.devices()
        if len(devs) < parallelism:
            raise ValueError(
                f"parallelism {parallelism} > {len(devs)} devices")
        return Mesh(_np.asarray(devs[:parallelism]), ("d",))

    async def _create_mv(self, stmt: ast.CreateMaterializedView) -> str:
        self.catalog._check_free(stmt.name)    # validate BEFORE planning
        async with self._barrier_lock:
            planner = StreamPlanner(self.catalog, self.store, self.local,
                                    definition="", mesh=self.mesh,
                                    actors=self.actors,
                                    join_state_cap=self.join_state_cap,
                                    state_tier_cap=self.state_tier_cap
                                    or None,
                                    chunk_target_rows=self
                                    .chunk_target_rows,
                                    coalesce_linger_chunks=self
                                    .coalesce_linger_chunks)
            actor_id = self._next_actor
            self._next_actor += 1
            id_base = self.catalog._next_id
            rules = self.session_vars.get("stream_rewrite_rules")
            from risingwave_tpu.frontend.opt import parse_fusion
            fusion = parse_fusion(self.session_vars.get("stream_fusion"))
            try:
                plan = planner.plan(
                    stmt.name, stmt.select, actor_id,
                    rate_limit=self.rate_limit,
                    min_chunks=self.min_chunks,
                    emit_on_window_close=getattr(
                        stmt, "emit_on_window_close", False))
                # plan-rewrite pass (frontend/opt): runs between the
                # planner and deployment; the checker falls back to
                # the unrewritten plan on any invariant violation
                from risingwave_tpu.frontend.opt import apply_rewrites
                apply_rewrites(plan, rules, label=stmt.name,
                               fusion=fusion)
            except BaseException:
                # a failed plan must leak nothing: source senders were
                # registered during planning and would wedge the next
                # barrier round (messages pile into unconsumed channels)
                for sid in planner.registered_senders:
                    self.local.drop_actor(sid)
                raise
            plan.mv.id_base = id_base
            await self._deploy_job(
                stmt.name, actor_id, plan.consumer, plan.readers,
                lambda: self.catalog.add_mv(plan.mv),
                attaches=plan.attaches,
                deps=plan.mv.dependent_sources)
        self._mv_selects[stmt.name] = (
            stmt.select, getattr(stmt, "emit_on_window_close", False))
        self._mv_rules[stmt.name] = rules
        self._mv_fusion[stmt.name] = fusion
        # CREATE-time tier cap: reschedule replans under it (the cap
        # shapes join state-table pk layouts — id-base contract)
        self._mv_tier_caps[stmt.name] = self.state_tier_cap or None
        if self._deployed_actor.failure is not None:
            # a failed CREATE deployed far enough to register {mv=...}
            # series — purge them before surfacing the failure, or the
            # dead job haunts the exposition (series-lifecycle rule)
            from risingwave_tpu.stream.costs import purge_mv_series
            purge_mv_series(stmt.name)
            raise self._deployed_actor.failure
        return "CREATE_MATERIALIZED_VIEW"

    async def _create_table(self, stmt: ast.CreateTable) -> str:
        """CREATE TABLE: a DML-fed streaming job (DmlReader source →
        materialize) so table writes ride the barrier pipeline and MV
        chains over tables work like MV-on-MV (handler/create_table.rs
        + dml_manager.rs analog). No PRIMARY KEY → hidden _row_id."""
        from risingwave_tpu.common.types import DataType, Field, Schema
        from risingwave_tpu.connectors.dml import DmlReader, RowIdSeq
        from risingwave_tpu.state.state_table import StateTable
        from risingwave_tpu.stream.exchange import channel_for_test
        from risingwave_tpu.stream.executors.materialize import (
            MaterializeExecutor,
        )
        from risingwave_tpu.stream.executors.source import SourceExecutor

        self.catalog._check_free(stmt.name)
        fields = []
        for cname, tname in stmt.columns:
            if any(f.name == cname for f in fields):
                raise PlanError(f"duplicate column {cname!r}")
            try:
                fields.append(Field(cname, DataType.from_sql(tname)))
            except KeyError:
                raise PlanError(f"unknown type {tname!r}")
        names = [f.name for f in fields]
        for c in stmt.pk_cols:
            if c not in names:
                raise PlanError(f"PRIMARY KEY column {c!r} not found")
        if stmt.pk_cols:
            schema = Schema(fields)
            pk = [names.index(c) for c in stmt.pk_cols]
            rowid = None
        else:
            schema = Schema(fields + [Field("_row_id",
                                            DataType.SERIAL)])
            pk = [len(fields)]
            rowid = RowIdSeq()
        async with self._barrier_lock:
            actor_id = self._next_actor
            self._next_actor += 1
            id_base = self.catalog._next_id
            sid = self.catalog.next_id()
            table_id = self.catalog.next_id()
            reader = DmlReader(schema)
            tx, rx = channel_for_test(edge=f"dml:{stmt.name}")
            self.local.register_sender(sid, tx)
            try:
                src = SourceExecutor(reader, rx, None, actor_id=sid,
                                     freshness_key=stmt.name)
                table = StateTable(table_id, schema, pk, self.store)
                mat = MaterializeExecutor(src, table,
                                          mv_name=stmt.name)
                mv = MvCatalog(stmt.name, table_id, schema, pk,
                               definition="", actor_id=actor_id,
                               id_base=id_base,
                               n_visible=len(fields) if rowid is not None
                               else None, is_table=True)
                await self._deploy_job(stmt.name, actor_id, mat,
                                       {sid: reader},
                                       lambda: self.catalog.add_mv(mv),
                                       freshness_sources=[stmt.name])
            except BaseException:
                self.local.drop_actor(sid)
                raise
        self._tables[stmt.name] = (reader, schema, pk, rowid,
                                   table_id)
        if self._deployed_actor.failure is not None:
            from risingwave_tpu.stream.costs import purge_mv_series
            purge_mv_series(stmt.name)
            raise self._deployed_actor.failure
        return "CREATE_TABLE"

    async def _drop_table(self, stmt: ast.DropTable) -> str:
        if stmt.name not in self._tables:
            if stmt.if_exists and stmt.name not in self.catalog.mvs:
                return "DROP_TABLE"
            if stmt.name not in self.catalog.mvs:
                raise PlanError(f"unknown table {stmt.name!r}")
            raise PlanError(f"{stmt.name!r} is not a table")
        dependents = [m.name for m in self.catalog.mvs.values()
                      if stmt.name in m.dependent_sources] + \
                     [s.name for s in self.catalog.sinks.values()
                      if stmt.name in s.dependent_sources]
        if dependents:
            raise PlanError(f"cannot drop table {stmt.name!r}: "
                            f"depended on by {dependents}")
        status = await self._drop_job(stmt.name, self.catalog.mvs,
                                      stmt.if_exists, "DROP_TABLE")
        self._tables.pop(stmt.name, None)
        return status

    def _table_job(self, name: str):
        job = self._tables.get(name)
        if job is None:
            raise PlanError(f"{name!r} is not a table")
        return job

    async def _insert(self, stmt: ast.Insert) -> str:
        """INSERT ... VALUES: evaluate rows, push one chunk through
        the table's DML channel, and return only after the checkpoint
        that makes it durable+visible commits (batch insert.rs)."""
        from risingwave_tpu.common.chunk import DataChunk, StreamChunk
        from risingwave_tpu.common.types import Schema
        from risingwave_tpu.expr.expr import Cast
        from risingwave_tpu.frontend.binder import Binder, Scope

        reader, schema, _pk, rowid, _tid = self._table_job(stmt.table)
        data_fields = list(schema)[:-1] if rowid is not None \
            else list(schema)
        if stmt.select is not None:
            # INSERT INTO t SELECT …: batch-evaluate over the latest
            # committed snapshot, then coerce column-wise
            from risingwave_tpu.batch import collect
            ex = plan_batch(stmt.select, self.catalog, self.store,
                            self.store.committed_epoch(),
                            profiler=self.loop.profiler)
            if len(ex.schema) != len(data_fields):
                raise PlanError(
                    f"INSERT SELECT has {len(ex.schema)} columns, "
                    f"table has {len(data_fields)}")
            rows = self._coerce_rows(collect(ex), ex.schema,
                                     data_fields)
        else:
            from risingwave_tpu.common.types import Field
            binder = Binder(Scope.of(Schema([]), None))
            one = DataChunk.empty(Schema([]), capacity=8)
            one.visibility[0] = True
            tmp_sch = Schema([Field(f"_c{i}", f.data_type)
                              for i, f in enumerate(data_fields)])
            rows = []
            for r in stmt.rows:
                if len(r) != len(data_fields):
                    raise PlanError(
                        f"INSERT row has {len(r)} values, table has "
                        f"{len(data_fields)} columns")
                cols = []
                for e_ast, f in zip(r, data_fields):
                    b = binder.bind(e_ast)
                    if b.return_type != f.data_type:
                        b = Cast(b, f.data_type)
                    cols.append(b.eval(one))
                # to_pylist converts physical->LOGICAL (DECIMAL
                # unscales, bools); from_pydict at the push site
                # expects logical values
                rows.append(DataChunk(tmp_sch, cols,
                                      one.visibility).to_pylist()[0])
        if not rows:
            return "INSERT 0 0"
        if rowid is not None:
            ids = rowid.take(self.store.committed_epoch(), len(rows))
            rows = [r + (i,) for r, i in zip(rows, ids)]
        data = {f.name: [r[i] for r in rows]
                for i, f in enumerate(schema)}
        reader.push(StreamChunk.from_pydict(schema, data))
        await self._dml_flush()
        return f"INSERT 0 {len(rows)}"

    async def _dml_flush(self) -> None:
        """Make a just-pushed DML chunk durable AND visible before the
        statement returns. Two barrier rounds: the table's source is
        parked on its barrier channel, so the first barrier always
        precedes the chunk (it re-arms generation for the next epoch)
        and the second seals + checkpoints the epoch that carried
        it."""
        await self._barrier(force_checkpoint=True)
        await self._barrier(force_checkpoint=True)

    @staticmethod
    def _coerce_rows(rows, src_schema, dst_fields) -> List[tuple]:
        """Column-wise cast of batch-select output (LOGICAL rows)
        onto table types; returns logical rows for the DML channel.
        Positional temp names, NOT the real ones: a SELECT output may
        carry duplicate column names (aliases, join sides) and a
        name-keyed build would silently collapse them. The chunk
        round trip keeps the value domain honest — from_pydict takes
        logical values physical, to_pylist brings the cast results
        back logical (DECIMAL scale, bools)."""
        from risingwave_tpu.common.chunk import DataChunk
        from risingwave_tpu.common.types import Field, Schema
        from risingwave_tpu.expr.expr import Cast, InputRef

        if not rows:
            return []
        if all(s.data_type == d.data_type
               for s, d in zip(src_schema, dst_fields)):
            return [tuple(r) for r in rows]
        tmp_src = Schema([Field(f"_c{i}", f.data_type)
                          for i, f in enumerate(src_schema)])
        chunk = DataChunk.from_pydict(
            tmp_src, {f"_c{i}": [r[i] for r in rows]
                      for i in range(len(src_schema))})
        cols = [Cast(InputRef(i, s.data_type),
                     d.data_type).eval(chunk)
                for i, (s, d) in enumerate(zip(src_schema,
                                               dst_fields))]
        tmp_dst = Schema([Field(f"_c{i}", d.data_type)
                          for i, d in enumerate(dst_fields)])
        return DataChunk(tmp_dst, cols, chunk.visibility).to_pylist()

    def _snapshot_rows(self, table_id: int, schema, pk) -> List[tuple]:
        from risingwave_tpu.common.epoch import Epoch, EpochPair
        from risingwave_tpu.state.state_table import StateTable

        from risingwave_tpu.batch.storage_table import rows_to_chunk

        t = StateTable(table_id, schema, pk, self.store,
                       sanity_check=False)
        ce = self.store.committed_epoch()
        t.init_epoch(EpochPair(Epoch(ce + 1), Epoch(ce)))
        phys = [tuple(row) for _pk, row in t.iter_rows()]
        if not phys:
            return []
        # state rows are PHYSICAL (DECIMAL = scaled int64); everything
        # the DML channel re-ingests via from_pydict must be LOGICAL,
        # so convert through a chunk round trip
        return rows_to_chunk(schema, phys).to_pylist()

    def _match_rows(self, stmt_where, schema, rows):
        """The subset of rows a DML WHERE clause selects."""
        import numpy as np

        from risingwave_tpu.common.chunk import DataChunk
        from risingwave_tpu.frontend.binder import Binder, Scope

        if not rows:
            return []
        if stmt_where is None:
            return rows
        chunk = DataChunk.from_pydict(
            schema, {f.name: [r[i] for r in rows]
                     for i, f in enumerate(schema)})
        pred = Binder(Scope.of(schema, None)).bind(stmt_where)
        col = pred.eval(chunk)
        keep = np.asarray(col.values)[:len(rows)].astype(bool)
        if col.validity is not None:
            keep &= np.asarray(col.validity)[:len(rows)]
        return [r for r, k in zip(rows, keep) if k]

    async def _delete(self, stmt: ast.Delete) -> str:
        """DELETE: snapshot-scan the committed rows, push their
        retractions through the DML channel (batch delete.rs)."""
        from risingwave_tpu.common.chunk import Op, StreamChunk

        reader, schema, pk, _rowid, tid = self._table_job(stmt.table)
        rows = self._match_rows(
            stmt.where, schema, self._snapshot_rows(tid, schema, pk))
        if rows:
            data = {f.name: [r[i] for r in rows]
                    for i, f in enumerate(schema)}
            reader.push(StreamChunk.from_pydict(
                schema, data, ops=[Op.DELETE] * len(rows)))
            await self._dml_flush()
        return f"DELETE {len(rows)}"

    async def _update(self, stmt: ast.Update) -> str:
        """UPDATE: snapshot-scan, re-evaluate SET expressions over the
        matching rows, push UpdateDelete/UpdateInsert pairs."""
        from risingwave_tpu.common.chunk import DataChunk, Op, StreamChunk
        from risingwave_tpu.expr.expr import Cast
        from risingwave_tpu.frontend.binder import Binder, Scope

        reader, schema, pk, rowid, tid = self._table_job(stmt.table)
        names = [f.name for f in schema]
        settable = names[:-1] if rowid is not None else names
        sets = []
        binder = Binder(Scope.of(schema, None))
        for col, e_ast in stmt.sets:
            if col not in settable:
                raise PlanError(f"column {col!r} not found")
            b = binder.bind(e_ast)
            dt = schema[names.index(col)].data_type
            if b.return_type != dt:
                b = Cast(b, dt)
            sets.append((names.index(col), b))
        rows = self._match_rows(
            stmt.where, schema, self._snapshot_rows(tid, schema, pk))
        if rows:
            chunk = DataChunk.from_pydict(
                schema, {f.name: [r[i] for r in rows]
                         for i, f in enumerate(schema)})
            from risingwave_tpu.common.types import Field, Schema
            new_cols = {}
            for idx, b in sets:
                col = b.eval(chunk)
                one_sch = Schema([Field("_v",
                                        schema[idx].data_type)])
                new_cols[idx] = [r[0] for r in DataChunk(
                    one_sch, [col], chunk.visibility).to_pylist()]
            out_rows, ops = [], []
            new_pks = set()
            pk_touched = any(idx in pk for idx, _b in sets)
            for i, old in enumerate(rows):
                new = list(old)
                for idx, _b in sets:
                    new[idx] = new_cols[idx][i]
                if pk_touched:
                    kp = tuple(new[j] for j in pk)
                    if kp in new_pks:
                        # two updated rows landing on one key would
                        # collide inside a single chunk and kill the
                        # table's actor — fail the STATEMENT instead
                        raise PlanError(
                            "UPDATE would assign the primary key "
                            f"{kp!r} to more than one row")
                    new_pks.add(kp)
                out_rows += [old, tuple(new)]
                ops += [Op.UPDATE_DELETE, Op.UPDATE_INSERT]
            data = {f.name: [r[i] for r in out_rows]
                    for i, f in enumerate(schema)}
            reader.push(StreamChunk.from_pydict(schema, data,
                                                ops=ops))
            await self._dml_flush()
        return f"UPDATE {len(rows)}"

    async def _alter_parallelism(self, stmt: ast.AlterParallelism) -> str:
        """Runtime reschedule (meta/stream/scale.rs:717
        reschedule_actors analog, collapsed to the TPU design): pause
        the job at a stop barrier, replan the SAME definition over an
        n-device mesh FROM THE SAME TABLE-ID BASE (state tables keep
        their ids, so the redeployed executors recover every group/row
        through the normal recovery path), then resume. The sharded
        kernels' vnode routing makes the moved state land on its new
        owner shard automatically at rebuild."""
        name, n = stmt.name, stmt.parallelism
        mv = self.catalog.mvs.get(name)
        if mv is None:
            raise PlanError(f"unknown materialized view {name!r}")
        deps_on_me = [m.name for m in self.catalog.mvs.values()
                      if name in m.dependent_sources] + \
                     [s.name for s in self.catalog.sinks.values()
                      if name in s.dependent_sources]
        if deps_on_me or any(d in self.catalog.mvs
                             for d in mv.dependent_sources):
            raise PlanError(
                "ALTER ... SET PARALLELISM on chained MVs is not "
                "supported yet")
        if mv.id_base < 0:
            raise PlanError(f"{name!r} predates reschedule support")
        stored = self._mv_selects.get(name)
        if stored is None:
            raise PlanError(f"no CREATE statement on record for "
                            f"{name!r}")
        sel, eowc = stored
        mesh = self._mesh_for(n)
        async with self._barrier_lock:
            # 1) stop this job's actors at a barrier (keep state +
            # catalog — this is a pause, not a drop)
            old_actor = await self._stop_job(name, mv.actor_id)
            try:
                if old_actor is not None and \
                        old_actor.failure is not None:
                    raise old_actor.failure
                # 2) replan from the recorded id base → same state
                # tables (the id sequence is deterministic in the
                # definition; mesh choice allocates no ids)
                saved = self.catalog._next_id
                self.catalog._next_id = mv.id_base
                planner = StreamPlanner(
                    self.catalog, self.store, self.local,
                    definition="", mesh=mesh, actors=self.actors,
                    join_state_cap=self.join_state_cap,
                    state_tier_cap=self._mv_tier_caps.get(name),
                    chunk_target_rows=self.chunk_target_rows,
                    coalesce_linger_chunks=self
                    .coalesce_linger_chunks)
                actor_id = self._next_actor
                self._next_actor += 1
                try:
                    # same flags as the CREATE: the id-base replay
                    # contract requires the identical allocation
                    # sequence (an EOWC gate allocates a table id)
                    plan = planner.plan(name, sel, actor_id,
                                        rate_limit=self.rate_limit,
                                        min_chunks=self.min_chunks,
                                        emit_on_window_close=eowc)
                    # re-rewrite under the CREATE-time rule spec: the
                    # kept state tables carry the schemas that rewrite
                    # produced (e.g. pruned join sides)
                    from risingwave_tpu.frontend.opt import (
                        apply_rewrites,
                    )
                    apply_rewrites(plan,
                                   self._mv_rules.get(name, "all"),
                                   label=name,
                                   fusion=self._mv_fusion.get(
                                       name, False) and mesh is None)
                except BaseException:
                    for sid in planner.registered_senders:
                        self.local.drop_actor(sid)
                    self.catalog._next_id = saved
                    raise
                self.catalog._next_id = max(saved,
                                            self.catalog._next_id)
                plan.mv.id_base = mv.id_base
                del self.catalog.mvs[name]
                # 3) redeploy; executors recover from the kept tables
                await self._deploy_job(
                    name, actor_id, plan.consumer, plan.readers,
                    lambda: self.catalog.add_mv(plan.mv),
                    attaches=plan.attaches,
                    deps=plan.mv.dependent_sources)
            except BaseException as e:
                # the old pipeline is gone and cannot be restored:
                # degrade to DROPPED (state tables kept) rather than
                # leaving a catalog entry that serves frozen results
                self.catalog.mvs.pop(name, None)
                self._mv_selects.pop(name, None)
                self._mv_rules.pop(name, None)
                self._mv_fusion.pop(name, None)
                self._mv_tier_caps.pop(name, None)
                raise PlanError(
                    f"reschedule of {name!r} failed after teardown — "
                    f"the MV was dropped (state retained): {e}") from e
        if self._deployed_actor.failure is not None:
            raise self._deployed_actor.failure
        return "ALTER_MATERIALIZED_VIEW"

    async def _create_sink(self, stmt: ast.CreateSink) -> str:
        from risingwave_tpu.frontend.catalog import SinkCatalog
        from risingwave_tpu.frontend.planner import validate_sink_options
        # validate BEFORE planning registers any barrier sender: a
        # planner failure after registration would orphan the channel
        # and wedge every later barrier once its permits run out
        self.catalog._check_free(stmt.name)
        validate_sink_options(stmt.options)
        async with self._barrier_lock:
            planner = StreamPlanner(self.catalog, self.store, self.local,
                                    definition="", mesh=self.mesh,
                                    actors=self.actors,
                                    chunk_target_rows=self
                                    .chunk_target_rows,
                                    coalesce_linger_chunks=self
                                    .coalesce_linger_chunks)
            actor_id = self._next_actor
            self._next_actor += 1
            try:
                plan = planner.plan_sink(
                    stmt.select, stmt.options, actor_id,
                    rate_limit=self.rate_limit,
                    min_chunks=self.min_chunks,
                    sink_name=stmt.name,
                    append_only=stmt.append_only,
                    coordinator=self.sinks)
                from risingwave_tpu.frontend.opt import (
                    apply_rewrites, parse_fusion,
                )
                apply_rewrites(
                    plan,
                    self.session_vars.get("stream_rewrite_rules"),
                    label=stmt.name,
                    fusion=parse_fusion(
                        self.session_vars.get("stream_fusion")))
            except BaseException:
                for sid in planner.registered_senders:
                    self.local.drop_actor(sid)
                raise
            if plan.encoder is not None:
                # register only after the WHOLE plan validated. Fresh
                # create: truncate any uncommitted staging leftover at
                # the path (floor=-1 promotes nothing). Recovery
                # replay: sweep against the recovered checkpoint floor
                # — staged epochs the floor covers are durable
                # upstream, so the sweep PROMOTES them (completes the
                # manifest); younger staging truncates and replays
                self.sinks.register(
                    stmt.name, plan.encoder, n_writers=1,
                    deferred=True,
                    floor=(self.store.committed_epoch()
                           if self._replaying else -1))
            try:
                await self._deploy_job(
                    stmt.name, actor_id, plan.consumer, plan.readers,
                    lambda: self.catalog.add_sink(SinkCatalog(
                        stmt.name, actor_id, dict(stmt.options),
                        dependent_sources=plan.deps, mode=plan.mode,
                        n_writers=1)),
                    attaches=plan.attaches, deps=plan.deps)
            except BaseException:
                self.sinks.unregister(stmt.name)
                raise
        if self._deployed_actor.failure is not None:
            from risingwave_tpu.stream.costs import purge_mv_series
            purge_mv_series(stmt.name)
            self.sinks.unregister(stmt.name)
            raise self._deployed_actor.failure
        return "CREATE_SINK"

    async def _stop_job(self, name: str, actor_id: int):
        """Stop one job's actors at a barrier and remove its topology
        (caller holds the barrier lock). Returns the stopped Actor (or
        None) — shared by drop and reschedule; the sequence is delicate
        (a heartbeat between steps would hang on the stopped actor)."""
        stop_ids = frozenset(self.readers.get(name, {}).keys()
                             | {actor_id})
        await self.loop.inject_and_collect(
            mutation=StopMutation(stop_ids))
        task = self.tasks.pop(actor_id, None)
        if task is not None:
            await task
        actor = self.actors.pop(actor_id, None)
        for sid in self.readers.pop(name, {}):
            self.local.drop_actor(sid)
        self.local.drop_actor(actor_id)
        # detach this job's chain edges from upstream dispatchers: an
        # orphan output would block the upstream on exhausted channel
        # permits a few barriers later
        for uid, out in self.chain_edges.pop(name, []):
            up = self.actors.get(uid)
            if up is not None and up.dispatchers:
                d = up.dispatchers[0]
                d.update_outputs(
                    [o for o in d.outputs() if o is not out])
        # with the edges detached, release the stopped chain's input
        # receivers — drops their queue-depth series deterministically
        if actor is not None:
            from risingwave_tpu.stream.actor import close_receivers
            close_receivers(actor.consumer)
        self.local.set_expected_actors(list(self.actors))
        if self._plane is not None:
            # drop the job from its alignment domain (an empty domain
            # retires — its frontier epoch stops blocking the fence)
            self._plane.remove_job(name)
        # central series-lifecycle purge: freshness, costs, hot-key
        # and topology books (and their {mv=...} series) all die with
        # the job — stream/costs.py owns the fan-out
        from risingwave_tpu.stream.costs import purge_mv_series
        purge_mv_series(name)
        return actor

    async def _drop_job(self, name: str, registry, if_exists: bool,
                        status: str) -> str:
        """Shared drop path for MVs and sinks: stop barrier + topology
        removal as ONE locked unit."""
        entry = registry.get(name)
        if entry is None:
            if if_exists:
                return status
            raise PlanError(f"unknown object {name!r}")
        async with self._barrier_lock:
            actor = await self._stop_job(name, entry.actor_id)
        del registry[name]
        # epoch-segment sinks: deregister from the coordinator —
        # committed manifests stay durable at the path; any pending
        # (non-checkpointed) tail is dropped with the registration,
        # consistent with manifests never outrunning the floor
        self.sinks.unregister(name)
        self._mv_selects.pop(name, None)
        self._mv_rules.pop(name, None)
        self._mv_fusion.pop(name, None)
        self._mv_tier_caps.pop(name, None)
        if actor is not None and actor.failure is not None:
            raise actor.failure
        return status

    async def _drop_mv(self, stmt: ast.DropMaterializedView) -> str:
        if stmt.name in self._tables:
            # tables share catalog.mvs; dropping one here would orphan
            # its DML channel (writes then vanish into a dead reader)
            raise PlanError(
                f"{stmt.name!r} is a table — use DROP TABLE")
        dependents = [
            m.name for m in self.catalog.mvs.values()
            if stmt.name in m.dependent_sources
        ] + [
            sk.name for sk in self.catalog.sinks.values()
            if stmt.name in sk.dependent_sources
        ]
        if dependents:
            raise PlanError(
                f"cannot drop MV {stmt.name!r}: depended on by "
                f"{dependents}")
        return await self._drop_job(stmt.name, self.catalog.mvs,
                                    stmt.if_exists,
                                    "DROP_MATERIALIZED_VIEW")

    async def _select(self, sel: ast.Select) -> Rows:
        from risingwave_tpu.batch import collect
        epoch = self.store.committed_epoch()
        ex = plan_batch(sel, self.catalog, self.store, epoch,
                        profiler=self.loop.profiler)
        # one plan serves both rows and result typing (pgwire reads
        # this right after execute instead of re-planning)
        self.last_select_schema = ex.schema
        return collect(ex)
