"""Deterministic chaos harness: a seeded fault schedule replayed
against a distributed session.

The madsim stance (SURVEY §4, already adopted by utils/failpoint.py):
fault schedules are DETERMINISTIC and reproducible — a chaos run is an
experiment you can replay, not a dice roll you describe. A seed fully
determines the schedule (which faults, at which barrier steps, on
which worker slots), every fault is injected at a step boundary, and
the recovery supervisor's classification of each induced failure is a
function of the fault — so the same seed reproduces the same
(cause, action) recovery sequence, which tests assert literally.

Fault vocabulary (each exercises one rung of the response ladder):

- ``flake_object_store`` — one transient PUT failure inside a worker,
  UNDER the RetryingObjectStore budget: absorbed in place, retry
  metrics move, NO recovery event.
- ``kill_worker`` — SIGKILL one worker subprocess mid-epoch: the next
  barrier round fails, the supervisor classifies ``dead_worker`` and
  respawns only the dead slot (live slots reset in place).
- ``fail_upload`` — a worker's checkpoint upload fails PAST the retry
  budget: surfaces as a worker-side OSError, classified
  ``storage_fault``, full recovery (which also replaces the faulty
  process, healing the injected fault — like swapping a dying disk).
- ``straggler`` — one executor sleeps past the barrier collect
  timeout: ``BarrierWedgedError``, classified ``wedged_barrier``,
  full recovery.

Mid-rescale faults (ISSUE 15 — every scaling action chaos-tested the
same way the recovery ladder was proven; each event arms the fault
THEN drives a guarded rescale through the session's ALTER path, the
same protocol the autoscaler drives):

- ``kill_mid_rescale`` — SIGKILL one worker exactly at the cohort
  REDEPLOY phase (the cluster's one-shot rescale fault hook). The
  rollback cannot complete against a dead slot, so the supervised
  ladder finishes the job: ``dead_worker``/respawn at the prior
  topology, with the rollback attempt in ``rw_recovery``.
- ``fault_mid_handoff`` — one worker's ``ingest_table`` RPC raises
  during the STATE HANDOFF (worker.rpc failpoint, times=1): the
  guarded rescale reverses the moved rows from its in-memory log and
  rolls back to the prior parallelism — no recovery needed, the
  domain keeps serving.
- ``straggler_mid_rescale`` — an executor sleeps past the collect
  timeout under the rescale's STOP barrier: the failure lands before
  any change (``phase="stop"``), the domain's health is unknown, and
  the supervisor answers ``wedged_barrier``/full.

Compactor-domain faults (ISSUE 19 — the dedicated compaction subsystem
rides the same ladder; both kinds require ``storage_compaction =
'dedicated'`` on the session under test):

- ``kill_compactor_mid_task`` — SIGKILL the compactor subprocess while
  a leased task may be in flight: the next ``compaction_tick``
  respawns the role, the orphaned lease expires and the task REQUEUES
  against the current version. Classified ``compactor_dead``/requeue
  in ``rw_recovery`` — a COMPACTOR-domain entry, never a serving
  recovery (the storm gate doesn't budget it, serving never stalls).
- ``storage_fault_during_vacuum`` — a worker's ``hummock.vacuum``
  failpoint raises during retired-SST deletion: pin-exact GC is
  delay-only (each entry deletes under its own try), so garbage
  lingers until a later vacuum pass and NO recovery of any kind is
  recorded.

Sink-domain faults (ISSUE 20 — the exactly-once epoch-segment sink
chaos-proven on both halves of its visibility rule; the schedule's
``rescale_mv`` names the SINK job when these kinds are present):

- ``kill_writer_mid_stage`` — wedge one writer INSIDE its synchronous
  segment stage (``sink.stage.mid`` sleep, fired at barrier passage
  before collection), then SIGKILL the slot while it sleeps there.
  The epoch's segment is absent or torn and UNMANIFESTED, the barrier
  round fails, ``dead_worker``/respawn — and the recovery sweep
  truncates the orphaned staging, so the epoch's rows replay under a
  fresh epoch. Exactly-once half one: nothing uncommitted is visible.
- ``fault_manifest_commit`` — the COORDINATOR's manifest PUT raises
  once during ``commit_upto`` (in-process failpoint: the commit half
  runs on the barrier owner, not in workers). The checkpoint floor
  has already advanced past the epoch, so recovery PROMOTES it from
  the durable staged listing. Exactly-once half two: a floor-covered
  epoch is never lost, and the idempotent manifest re-PUT never
  duplicates.
- ``rescale_sink_fragment`` — a clean guarded rescale of the sink
  job's fragment mid-stream (the session ALTER path): stop-and-align
  forces a checkpoint (staged + committed through the stop barrier),
  redeploy re-stamps writer ranks, and the output must stay oracle-
  identical across the N-writers → M-writers handoff.

Faults inject into LIVE worker processes over the control channel's
``arm_failpoints`` verb (exception specs are JSON — the failpoint
env/wire restriction), so a respawned worker always comes back clean.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from risingwave_tpu.meta.supervisor import RecoveryEvent

# absorbable flake: strictly under RetryingObjectStore's default
# retry budget (3) so the bottom rung provably swallows it
_FLAKE_TIMES = 1
# terminal upload fault: strictly past the same budget
_FAULT_TIMES = 16


@dataclass(frozen=True)
class ChaosEvent:
    """One scheduled fault: injected right before barrier `step`."""

    step: int
    kind: str          # flake_object_store|kill_worker|fail_upload|straggler
    slot: int

    def row(self) -> tuple:
        return (self.step, self.kind, self.slot)


def generate_schedule(seed: int, n_workers: int = 2,
                      steps: int = 24,
                      kinds: Optional[List[str]] = None
                      ) -> List[ChaosEvent]:
    """Seeded schedule with guaranteed coverage: one fault of every
    kind (default: flake + SIGKILL + upload fault + straggler — the
    acceptance mix), at distinct PRNG-drawn steps/slots. Same seed ⇒
    same schedule, byte for byte."""
    rng = random.Random(seed)
    kinds = list(kinds if kinds is not None else (
        "flake_object_store", "kill_worker", "fail_upload",
        "straggler"))
    # termination bound for the rejection sampling below: each accepted
    # pick blocks at most 3 candidate steps (itself ±1), so the
    # candidate range (steps - 2 values) must outlast
    # 3 * (len(kinds) - 1) blocked ones with one to spare
    if steps < 3 * len(kinds):
        raise ValueError(
            f"schedule too dense: {len(kinds)} fault kinds need "
            f"steps >= {3 * len(kinds)}, got {steps}")
    # distinct steps, ≥2 apart, leaving step 0/1 for pipeline spin-up:
    # two faults in the same round would make WHICH failure surfaces
    # first racy, and determinism of the recovery sequence is the point
    picks: List[int] = []
    while len(picks) < len(kinds):
        s = rng.randrange(2, steps)
        if all(abs(s - p) >= 2 for p in picks):
            picks.append(s)
    rng.shuffle(kinds)
    return sorted(
        (ChaosEvent(s, k, rng.randrange(n_workers))
         for s, k in zip(picks, kinds)),
        key=lambda e: (e.step, e.kind))


# mid-rescale fault kinds: each arms its fault, then drives a guarded
# rescale (the session ALTER path — the same protocol the autoscaler
# drives) so the fault lands inside the named rescale phase
RESCALE_KINDS = frozenset({"kill_mid_rescale", "fault_mid_handoff",
                           "straggler_mid_rescale"})

# compactor-domain fault kinds (ISSUE 19): only meaningful when the
# session under test runs storage_compaction='dedicated'
COMPACTOR_KINDS = frozenset({"kill_compactor_mid_task",
                             "storage_fault_during_vacuum"})

# sink-domain fault kinds (ISSUE 20): exercise both halves of the
# epoch-segment visibility rule plus the rescale handoff; the schedule
# needs rescale_mv = the SINK job's name for the rescale kind
SINK_KINDS = frozenset({"kill_writer_mid_stage",
                        "fault_manifest_commit",
                        "rescale_sink_fragment"})

# how long the wedged writer sleeps inside stage() vs. how long the
# harness waits before SIGKILLing the slot: the kill must land while
# the writer is provably INSIDE the staging window
_STAGE_WEDGE_S = 1.5
_STAGE_KILL_AFTER_S = 0.4


@dataclass
class ChaosReport:
    """What a chaos run produced — the bench-snapshot payload and the
    determinism assertion's subject."""

    seed: int
    events: List[tuple] = field(default_factory=list)    # applied
    recoveries: List[tuple] = field(default_factory=list)  # (cause, action)
    mttr_s: List[float] = field(default_factory=list)
    absorbed_retries: Dict[str, float] = field(default_factory=dict)
    # guarded rescales that unwound in place: (phase, rolled_back) —
    # rolled_back=True means no recovery was needed
    rescale_rollbacks: List[tuple] = field(default_factory=list)
    wall_s: float = 0.0

    def summary(self) -> dict:
        return {
            "seed": self.seed,
            "wall_s": self.wall_s,
            "events": [list(e) for e in self.events],
            "recoveries": [list(r) for r in self.recoveries],
            "recovery_count": len(self.recoveries),
            "mttr_mean_s": (sum(self.mttr_s) / len(self.mttr_s)
                            if self.mttr_s else 0.0),
            "mttr_max_s": max(self.mttr_s, default=0.0),
            "absorbed_retries": dict(self.absorbed_retries),
            "rescale_rollbacks": [list(r)
                                  for r in self.rescale_rollbacks],
        }


class ChaosRunner:
    """Replay a schedule against a DistFrontend: inject each fault at
    its step boundary, drive barriers, feed every failure to the
    supervised-recovery path, then settle the pipeline to completion.
    The caller owns the oracle comparison (and the frontend)."""

    def __init__(self, fe, schedule: List[ChaosEvent], seed: int,
                 steps: int = 24, settle_steps: int = 40,
                 rescale_mv: Optional[str] = None):
        self.fe = fe
        self.schedule = list(schedule)
        self.seed = seed
        self.steps = steps
        self.settle_steps = settle_steps
        # the MV whose guarded rescale the mid-rescale faults target
        # (required when the schedule contains RESCALE_KINDS)
        self.rescale_mv = rescale_mv
        # delayed-SIGKILL task for kill_writer_mid_stage: fired during
        # the NEXT barrier step (while the wedged writer sleeps inside
        # stage()); awaited before the report returns
        self._pending_kill = None
        if any(e.kind in RESCALE_KINDS
               or e.kind == "rescale_sink_fragment"
               for e in self.schedule):
            assert rescale_mv is not None, (
                "a mid-rescale fault schedule needs rescale_mv")
        if any(e.kind in COMPACTOR_KINDS for e in self.schedule):
            assert fe.cluster._compaction_mode == "dedicated", (
                "a compactor fault schedule needs the session under "
                "test to SET storage_compaction = 'dedicated' first")
        if any(e.kind in ("straggler", "straggler_mid_rescale")
               for e in self.schedule):
            assert fe.cluster.barrier_timeout_s is not None, (
                "a straggler fault needs wedged-barrier detection: "
                "construct the DistFrontend with barrier_timeout_s")

    async def _arm(self, slot: int, points: dict) -> None:
        await self.fe.cluster.clients[slot].call_idempotent(
            {"cmd": "arm_failpoints", "points": points})

    def _alter_target(self) -> int:
        """Deterministic rescale target: shrink a scaled job, grow a
        single-actor one (the first rescalable fragment decides)."""
        job = self.fe.cluster.jobs[self.rescale_mv]
        for fi, f in enumerate(job.graph.fragments):
            if self.fe.cluster._rescalable(f) \
                    or self.fe.cluster._source_rescalable(f):
                return 1 if len(job.placements[fi]) >= 2 else 2
        return 2

    async def _alter_supervised(self, report: ChaosReport) -> None:
        """Drive the guarded rescale with the fault armed. A clean
        rollback needs no recovery (the protocol's point); a rollback
        that could not complete feeds the supervised ladder like any
        other failure."""
        from risingwave_tpu.cluster.scheduler import RescaleError
        n = self._alter_target()
        try:
            await self.fe.execute(
                f"ALTER MATERIALIZED VIEW {self.rescale_mv} "
                f"SET PARALLELISM = {n}")
        except RescaleError as e:
            report.rescale_rollbacks.append((e.phase, e.rolled_back))
            if not e.rolled_back:
                rec = await self.fe.supervised_recover(e)
                report.recoveries.append((rec.cause, rec.action))
                report.mttr_s.append(rec.duration_s)
        except Exception as e:  # noqa: BLE001 — the supervisor's job
            rec = await self.fe.supervised_recover(e)
            report.recoveries.append((rec.cause, rec.action))
            report.mttr_s.append(rec.duration_s)

    async def _apply(self, ev: ChaosEvent,
                     report: ChaosReport) -> None:
        if ev.kind == "kill_worker":
            self.fe.cluster.kill_slot(ev.slot)
        elif ev.kind == "flake_object_store":
            await self._arm(ev.slot, {"object_store.upload": {
                "raise": "OSError", "msg": "chaos flake",
                "times": _FLAKE_TIMES}})
        elif ev.kind == "fail_upload":
            await self._arm(ev.slot, {"object_store.upload": {
                "raise": "OSError", "msg": "chaos upload fault",
                "times": _FAULT_TIMES}})
        elif ev.kind == "straggler":
            timeout = self.fe.cluster.barrier_timeout_s
            await self._arm(ev.slot, {"trace.slow.HashAggExecutor": {
                "sleep_s": timeout * 2.5, "times": 1}})
        elif ev.kind == "kill_mid_rescale":
            slot = ev.slot
            self.fe.cluster.rescale_fault_hook = (
                "redeploy", lambda: self.fe.cluster.kill_slot(slot))
            try:
                await self._alter_supervised(report)
            finally:
                # the hook disarms when it FIRES; if the ALTER failed
                # before reaching the redeploy phase it would stay
                # armed and fire during a later, unscheduled rescale —
                # decoupling the fault from its seeded ChaosEvent step
                self.fe.cluster.rescale_fault_hook = None
        elif ev.kind == "fault_mid_handoff":
            await self._arm(ev.slot, {"worker.rpc.ingest_table": {
                "raise": "OSError", "msg": "chaos handoff fault",
                "times": 1}})
            await self._alter_supervised(report)
        elif ev.kind == "kill_compactor_mid_task":
            # the slot is irrelevant — there is ONE compactor role; a
            # kill between tasks (nothing leased) must also converge,
            # so the event never waits for a task to be in flight
            self.fe.cluster.kill_compactor()
        elif ev.kind == "storage_fault_during_vacuum":
            await self._arm(ev.slot, {"hummock.vacuum": {
                "raise": "OSError", "msg": "chaos vacuum fault",
                "times": 4}})
        elif ev.kind == "kill_writer_mid_stage":
            # arm the wedge on the worker, then SIGKILL it a beat into
            # the next barrier step — the writer dies INSIDE stage(),
            # leaving an unmanifested (possibly torn) segment that the
            # recovery sweep must truncate before the rows replay
            import asyncio
            await self._arm(ev.slot, {"sink.stage.mid": {
                "sleep_s": _STAGE_WEDGE_S, "times": 1}})
            slot = ev.slot

            async def _delayed_kill():
                await asyncio.sleep(_STAGE_KILL_AFTER_S)
                self.fe.cluster.kill_slot(slot)

            self._pending_kill = asyncio.create_task(_delayed_kill())
        elif ev.kind == "fault_manifest_commit":
            # the manifest commit runs on the COORDINATOR (this
            # process), not in a worker — arm the local registry, not
            # the control channel. times=1: the re-derived commit
            # after recovery must succeed
            from risingwave_tpu.utils.failpoint import arm_specs
            arm_specs({"sink.manifest_commit": {
                "raise": "OSError", "msg": "chaos manifest fault",
                "times": 1}})
        elif ev.kind == "rescale_sink_fragment":
            # no fault armed: the guarded rescale ITSELF is the event
            # (stop-and-align checkpoint → writer-rank re-stamp) and
            # exactly-once across the handoff is the assertion
            await self._alter_supervised(report)
        elif ev.kind == "straggler_mid_rescale":
            timeout = self.fe.cluster.barrier_timeout_s
            await self._arm(ev.slot, {"trace.slow.HashAggExecutor": {
                "sleep_s": timeout * 2.5, "times": 1}})
            await self._alter_supervised(report)
        else:
            raise ValueError(f"unknown chaos event kind {ev.kind!r}")

    async def _step_supervised(self, report: ChaosReport) -> None:
        try:
            await self.fe.step(1)
            self.fe.cluster.supervisor.note_healthy()
        except Exception as e:  # noqa: BLE001 — the supervisor's job
            rec: RecoveryEvent = await self.fe.supervised_recover(e)
            report.recoveries.append((rec.cause, rec.action))
            report.mttr_s.append(rec.duration_s)

    async def run(self) -> ChaosReport:
        report = ChaosReport(self.seed)
        by_step: Dict[int, List[ChaosEvent]] = {}
        for ev in self.schedule:
            by_step.setdefault(ev.step, []).append(ev)
        for i in range(self.steps):
            for ev in by_step.get(i, ()):
                await self._apply(ev, report)
                report.events.append(ev.row())
            await self._step_supervised(report)
        # settle: drain the sources to completion so the MV is final
        # (recoveries rewind to the committed epoch — later faults cost
        # re-processing, so the settle budget is generous)
        for _ in range(self.settle_steps):
            await self._step_supervised(report)
        if self._pending_kill is not None:
            await self._pending_kill
            self._pending_kill = None
        if any(e.kind == "fault_manifest_commit"
               for e in self.schedule):
            # the manifest fault arms the LOCAL registry (times=1); if
            # the schedule landed it after the last commit it never
            # fired — disarm so it cannot leak into unrelated runs
            from risingwave_tpu.utils.failpoint import arm_specs
            arm_specs({"sink.manifest_commit": None})
        report.absorbed_retries = await worker_retry_totals(self.fe)
        return report


async def worker_retry_totals(fe) -> Dict[str, float]:
    """Sum object_store_retry_total across live worker processes
    (absorption happens inside workers; the coordinator's registry
    never sees it)."""
    totals: Dict[str, float] = {}
    for c in fe.cluster.clients:
        if c is None:
            continue
        text = (await c.call_idempotent({"cmd": "metrics"}))["text"]
        for line in text.splitlines():
            if line.startswith("object_store_retry_total{"):
                name, val = line.rsplit(" ", 1)
                totals[name] = totals.get(name, 0.0) + float(val)
    return totals


async def run_chaos(fe, seed: int, steps: int = 24,
                    settle_steps: int = 40,
                    kinds: Optional[List[str]] = None,
                    rescale_mv: Optional[str] = None) -> ChaosReport:
    """Generate + replay one seeded schedule (the bench entry point).
    Wall-clock MTTR is recorded per recovery by the supervisor.
    ``rescale_mv`` names the job the mid-rescale fault kinds drive
    their guarded ALTER against."""
    schedule = generate_schedule(seed, n_workers=fe.cluster.n,
                                 steps=steps, kinds=kinds)
    t0 = time.monotonic()
    report = await ChaosRunner(fe, schedule, seed, steps=steps,
                               settle_steps=settle_steps,
                               rescale_mv=rescale_mv).run()
    report.wall_s = time.monotonic() - t0
    return report
