"""Coordinator side: worker control client + cross-process barriers.

Reference parity: the meta service's GlobalBarrierManager talking to
compute nodes (barrier/mod.rs:558 inject → stream_service
InjectBarrier → BarrierComplete) and GlobalStreamManager's actor
deployment (stream_manager.rs:161) — the coordinator drives its OWN
BarrierLoop and the worker participates as one more "actor": a
registered barrier sender forwards each injection over the control
channel, and the worker's completion reply collects the pseudo-actor.
Everything the single-process session does (epochs, checkpoint
frequency, in-flight window, stats) is reused unchanged.
"""

from __future__ import annotations

import asyncio
import json
import subprocess
import sys
from typing import Optional

from risingwave_tpu.stream.message import (
    Barrier, PauseMutation, ResumeMutation, StopMutation,
)
from risingwave_tpu.utils.metrics import CLUSTER as _METRICS

# control-channel line framing, BOTH ends of every worker socket
# (replies are one JSON line each; scan_table/metrics payloads
# overflow asyncio's 64KB default, surfacing as an opaque
# ValueError), and the request/reply page budget derived from it:
# pages stay comfortably under the frame even with huge rows (an
# approx_count_distinct sketch row hex-encodes to ~100KB — whole-
# table replies broke the channel at real MV sizes). One constant
# pair so the two ends can never drift apart.
CONTROL_LINE_LIMIT = 1 << 24
CONTROL_PAGE_BYTES = 4 << 20

# verbs safe to RE-SEND after a reconnect: each is a pure read or an
# absolute-state write (recover_store/set_trace/arm_failpoints set a
# target state, so applying twice equals applying once). inject /
# deploy_plan / ingest_table / drain_trace are NOT here — replaying
# them changes cluster state, and their failures belong to the
# recovery supervisor, not a silent retry.
_IDEMPOTENT_VERBS = frozenset({
    "ping", "scan_table", "recover_store", "set_trace", "set_ledger",
    "arm_failpoints", "metrics", "reset",
    # pure reads: the autoscaler signal snapshot (tricolor + walker)
    # and the wedge-diagnostic await dump
    "signals", "awaits",
    # absolute-state write: sealing/syncing to an epoch twice equals
    # once (the aligned-checkpoint floor push, ISSUE 13)
    "seal_sync",
    # compaction plane: mode toggle is absolute state, the level
    # snapshot is a pure read, and aborting a task twice equals once
    # (reservation release + delete-if-present). compact_reserve /
    # compact_apply / compact_task are NOT here — replaying them
    # allocates ids or commits versions.
    "set_compaction", "level_snapshot", "compact_abort",
})


class WorkerClient:
    """JSON-lines control channel to one worker (MetaClient analog)."""

    def __init__(self, host: str, control_port: int,
                 exchange_port: int):
        self.host = host
        self.control_port = control_port
        self.exchange_port = exchange_port
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._lock = asyncio.Lock()

    async def connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.control_port, limit=CONTROL_LINE_LIMIT)

    async def call_idempotent(self, cmd: dict,
                              io_timeout: Optional[float] = None,
                              retries: int = 2,
                              backoff_s: float = 0.05) -> dict:
        """Transient-fault absorption for idempotent verbs: a torn or
        timed-out channel reconnects and re-sends instead of staying
        poisoned (the graduated-response ladder's RPC rung — a single
        timeout must not cost a full-cluster recovery). Out-of-retries
        errors surface to the caller/supervisor; each retry increments
        ``rpc_retry_total{verb=...}``."""
        verb = str(cmd.get("cmd"))
        if verb not in _IDEMPOTENT_VERBS:
            raise ValueError(
                f"refusing to auto-retry non-idempotent verb {verb!r}")
        delay = backoff_s
        for attempt in range(retries + 1):
            used = None
            try:
                # reconnect under the channel lock: two concurrent
                # callers on one shared client must not double-connect
                # (leaking a socket) — re-check after the await
                async with self._lock:
                    if self._writer is None:
                        await self.connect()
                    used = self._writer
                return await self.call(cmd, io_timeout=io_timeout)
            except (ConnectionError, OSError):
                if attempt >= retries:
                    raise
                _METRICS.rpc_retry.inc(verb=verb)
                # only tear down the channel WE failed on — a peer may
                # have already reconnected it while we were failing
                if self._writer is used:
                    self.abort()
                await asyncio.sleep(delay)
                delay *= 2
        raise AssertionError("unreachable")

    async def call(self, cmd: dict,
                   io_timeout: Optional[float] = None) -> dict:
        """One framed RPC. `io_timeout` bounds the round trip AFTER
        the channel lock is held (waiting behind another in-flight RPC
        is not evidence of a dead worker); an expired timeout leaves a
        desynchronized stream, so the channel is hard-closed."""
        if self._writer is None:
            raise ConnectionError("worker control channel closed")
        async with self._lock:
            if self._writer is None:
                raise ConnectionError("worker control channel closed")
            self._writer.write((json.dumps(cmd) + "\n").encode())
            await self._writer.drain()
            if io_timeout is None:
                line = await self._reader.readline()
            else:
                try:
                    line = await asyncio.wait_for(
                        self._reader.readline(), io_timeout)
                except asyncio.TimeoutError:
                    self.abort()
                    raise ConnectionError(
                        "worker control RPC timed out") from None
        if not line or not line.endswith(b"\n"):
            # closed, or a torn reply from a worker killed mid-write
            raise ConnectionError("worker control channel closed")
        reply = json.loads(line)
        if not reply.get("ok"):
            raise RuntimeError(f"worker error: {reply.get('error')}")
        return reply

    async def deploy_plan(self, plan: list, **params) -> dict:
        """Ship a plan-IR fragment (stream/plan_ir.py) — the typed
        StreamNode-shipping path (stream_plan.proto analog)."""
        return await self.call({"cmd": "deploy_plan", "plan": plan,
                                "params": params})

    _PAGE_BYTES = CONTROL_PAGE_BYTES

    async def scan_table(self, table_id: int,
                         epoch: Optional[int] = None) -> list:
        """Pull one table's committed rows (value-codec decoded) from
        the worker's namespace — the distributed-SELECT data plane.
        Pages through the worker's byte-budgeted replies (all pages
        pinned to the FIRST page's epoch) so one giant table never
        overflows the JSON-line channel."""
        from risingwave_tpu.storage.value_codec import decode_row
        out = []
        after = None
        while True:
            reply = await self.call_idempotent(
                {"cmd": "scan_table", "table_id": table_id,
                 "epoch": epoch, "after": after})
            out += [(bytes.fromhex(k), decode_row(bytes.fromhex(r)))
                    for k, r in reply["rows"]]
            if reply.get("done", True) or not reply["rows"]:
                return out
            epoch = reply["epoch"]        # later pages pin the snapshot
            after = reply["rows"][-1][0]

    async def ingest_table(self, table_id: int, rows: list,
                           min_epoch: Optional[int] = None) -> dict:
        """Bulk-load (key_bytes, row_tuple) pairs — state migration.
        `min_epoch` keeps the ingest epoch above in-flight barriers.
        Large batches split into byte-budgeted requests (each commits
        at its own fresh epoch; the returned epoch is the highest)."""
        from risingwave_tpu.storage.value_codec import encode_row
        batch, nbytes = [], 0
        total = 0
        top = None
        for k, v in rows:
            kx = k.hex()
            vx = None if v is None else encode_row(tuple(v)).hex()
            batch.append([kx, vx])
            nbytes += len(kx) + (len(vx) if vx else 0)
            if nbytes >= self._PAGE_BYTES:
                top = await self.call({
                    "cmd": "ingest_table", "table_id": table_id,
                    "min_epoch": max(min_epoch or 0,
                                     int(top["epoch"]) if top else 0),
                    "rows": batch})
                total += int(top["rows"])
                batch, nbytes = [], 0
        if batch or top is None:
            top = await self.call({
                "cmd": "ingest_table", "table_id": table_id,
                "min_epoch": max(min_epoch or 0,
                                 int(top["epoch"]) if top else 0),
                "rows": batch})
            total += int(top["rows"])
        return {"ok": True, "rows": total, "epoch": int(top["epoch"])}

    async def inject(self, barrier: Barrier,
                     committed: Optional[int] = None,
                     extras: Optional[dict] = None) -> dict:
        m = None
        if isinstance(barrier.mutation, StopMutation):
            m = {"type": "stop",
                 "actors": sorted(barrier.mutation.actors)}
        elif isinstance(barrier.mutation, PauseMutation):
            m = {"type": "pause"}
        elif isinstance(barrier.mutation, ResumeMutation):
            m = {"type": "resume"}
        cmd = {
            "cmd": "inject",
            "curr": barrier.epoch.curr.value,
            "prev": barrier.epoch.prev.value,
            "kind": barrier.kind.value,
            "mutation": m,
            # the coordinator's commit decision pipelined on this
            # barrier (two-phase workers adopt staged SSTs ≤ this)
            "committed": committed,
        }
        if extras:
            # barrier-domain frame (ISSUE 13): "actors" scopes the
            # barrier to one domain's actors on the worker; "seal"
            # carries the cross-domain write floor the worker may
            # fence to (per-domain prevs interleave globally, so the
            # worker must never seal to its own prev eagerly)
            cmd.update(extras)
        from risingwave_tpu.utils import spans as _spans
        if _spans.enabled():
            # span context rides the injection: worker-side spans of
            # this barrier round parent to the coordinator's inject
            # span — the cross-process causal edge
            cmd["trace"] = {
                "span": _spans.EPOCH_TRACER.root_id(
                    barrier.epoch.curr.value)}
        return await self.call(cmd)

    async def ping(self, io_timeout: float = 2.0,
                   retries: int = 1) -> dict:
        """Heartbeat probe (cluster.rs heartbeat RPC round trip). One
        timed-out or torn round trip reconnects and retries: a single
        slow reply is a transient, not a death certificate — the lease
        in ClusterManager is what decides expiry."""
        return await self.call_idempotent({"cmd": "ping"},
                                          io_timeout=io_timeout,
                                          retries=retries)

    def abort(self) -> None:
        """Hard-close the channel. The JSON-lines protocol has no
        correlation ids, so once a framed call is cancelled mid-read
        (ping timeout) the stream is desynchronized — a late reply
        would be read as the NEXT call's response. Closing makes every
        later call fail loudly instead."""
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._reader = None

    async def stop(self) -> None:
        try:
            await self.call({"cmd": "stop"})
        except (ConnectionError, RuntimeError):
            pass
        if self._writer is not None:
            self._writer.close()


class Heartbeater:
    """Coordinator-side liveness loop: ping every registered worker on
    an interval, feed the ClusterManager, expire the silent ones
    (meta/src/manager/cluster.rs:360 check loop + the compute node's
    heartbeat sender, combined at the meta side since the coordinator
    owns the control channel)."""

    def __init__(self, cluster, interval_s: float = 1.0,
                 on_expired=None):
        self.cluster = cluster
        self.interval = interval_s
        self._clients: dict = {}          # worker_id → WorkerClient
        self._task = None
        # owner callback invoked with the evicted WorkerNode list —
        # the supervisor's heartbeat-expiry detection input (tick used
        # to compute the dead set and drop it on the floor)
        self.on_expired = on_expired

    def register(self, worker_id: int, client: WorkerClient) -> None:
        self._clients[worker_id] = client

    async def tick(self) -> list:
        """One round: ping all CONCURRENTLY (a dead worker's timeout
        must not consume a healthy worker's lease), heartbeat the
        responders, expire the rest. Returns the evicted workers.
        The ping's io-timeout starts after the channel lock is held —
        waiting behind a long barrier RPC never counts against the
        worker, and call() closes a genuinely desynced channel itself."""
        async def one(wid, client):
            try:
                reply = await client.ping()
            except (ConnectionError, RuntimeError, OSError,
                    ValueError):            # incl. torn-reply JSON
                return                     # no heartbeat → may expire
            if not self.cluster.heartbeat(wid, reply.get("info")):
                # expired/removed outside this loop: stop pinging it
                stale = self._clients.pop(wid, None)
                if stale is not None:
                    stale.abort()

        await asyncio.gather(*(one(w, c)
                               for w, c in list(self._clients.items())))
        dead = self.cluster.expire_stale()
        for w in dead:
            _METRICS.worker_expired.inc(worker=str(w.worker_id))
            client = self._clients.pop(w.worker_id, None)
            if client is not None:
                client.abort()             # no leaked half-open socket
        if dead and self.on_expired is not None:
            self.on_expired(dead)
        return dead

    def start(self) -> None:
        async def loop():
            while True:
                await asyncio.sleep(self.interval)
                await self.tick()

        self._task = asyncio.ensure_future(loop())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


class WorkerBarrierSender:
    """Shaped like an exchange Sender: the coordinator's barrier
    manager 'sends' each barrier to the worker over control, and the
    worker's completion reply collects the pseudo-actor — InjectBarrier
    + BarrierComplete as one round trip."""

    # phase-ledger hint (meta/barrier.py seal): actor work behind this
    # sender runs in ANOTHER process, so coordinator-side conservation
    # is meaningless until drain_ledger merges the worker's books
    remote = True

    def __init__(self, client: WorkerClient, local, pseudo_actor: int,
                 committed_fn=None, extras_fn=None):
        self.client = client
        self.local = local
        self.pseudo = pseudo_actor
        # reads the coordinator's committed epoch at send time (the
        # commit decision pipelined onto each barrier); None = legacy
        # self-committing workers
        self.committed_fn = committed_fn
        # barrier-domain frame builder (ISSUE 13): called per send
        # with the barrier, returns the domain actor filter + seal
        # floor to ride the inject cmd; None = legacy global frames
        self.extras_fn = extras_fn
        self._tasks: set = set()   # strong refs: the loop holds tasks
        #                            weakly and could drop one mid-RPC

    async def send(self, barrier: Barrier) -> None:
        committed = (self.committed_fn()
                     if self.committed_fn is not None else None)
        extras = (self.extras_fn(barrier)
                  if self.extras_fn is not None else None)

        async def roundtrip():
            try:
                await self.client.inject(barrier, committed, extras)
                self.local.collect(self.pseudo, barrier)
            except BaseException as e:  # noqa: BLE001 — fail the epoch
                self.local.notify_failure(self.pseudo, e)

        t = asyncio.ensure_future(roundtrip())
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)

    def close(self) -> None:
        pass


class WorkerHandle:
    """Spawn + own a worker subprocess (GlobalStreamManager's node).
    ``role="compactor"`` spawns the dedicated merge executor instead —
    same boot/heartbeat/kill lifecycle, no exchange plane."""

    def __init__(self, store_dir: str, platform: str = "cpu",
                 role: str = "worker"):
        self.store_dir = store_dir
        self.platform = platform
        self.role = role
        self.proc: Optional[subprocess.Popen] = None
        self.client: Optional[WorkerClient] = None

    async def start(self, timeout_s: float = 60.0) -> WorkerClient:
        import os
        env = dict(os.environ)
        # pin, don't setdefault: an ambient JAX_PLATFORMS naming an
        # accelerator (e.g. a tunneled TPU) would otherwise leak into
        # every worker, and a worker's first jax op blocks forever if
        # that tunnel is down. Callers opt INTO an accelerator via
        # platform=; the default worker is a CPU host process.
        env["JAX_PLATFORMS"] = self.platform
        argv = [sys.executable, "-m", "risingwave_tpu.cluster.worker",
                "--store", self.store_dir]
        if self.role != "worker":
            argv += ["--role", self.role]
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, cwd=None, text=True)
        loop = asyncio.get_event_loop()
        try:
            line = await asyncio.wait_for(
                loop.run_in_executor(None, self.proc.stdout.readline),
                timeout_s)
        except (asyncio.TimeoutError, TimeoutError):
            self.kill()                 # no orphan on a hung boot
            raise
        ports = json.loads(line)
        self.client = WorkerClient("127.0.0.1", ports["control_port"],
                                   ports["exchange_port"])
        await self.client.connect()
        return self.client

    def alive(self) -> bool:
        """Subprocess liveness (the supervisor's cheapest detection
        input): started, not yet reaped, and not exited."""
        return self.proc is not None and self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — the chaos path (no goodbye, no flush)."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait()
            self.proc = None

    async def stop(self) -> None:
        if self.client is not None:
            await self.client.stop()
        if self.proc is not None:
            loop = asyncio.get_event_loop()
            try:
                await asyncio.wait_for(
                    loop.run_in_executor(None, self.proc.wait), 20)
            except (asyncio.TimeoutError, TimeoutError):
                self.kill()             # wedged worker: no orphan
            self.proc = None
