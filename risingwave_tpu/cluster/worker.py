"""Worker node: actors + exchange server + control channel.

Reference parity: the compute node (src/compute/src/server.rs:85) —
hosts actors, serves its outputs over the exchange (stream/remote.py),
executes barrier injections from the coordinator and reports collection
(stream_service.proto InjectBarrier/BarrierComplete), owns a local
state-store namespace whose checkpoints commit at the SAME epochs the
coordinator drives, so a recovering cluster resumes consistently from
the coordinator's committed epoch.

Fragments deploy two ways: by SHIPPED PLAN IR (``deploy_plan`` — the
stream_plan.proto analog; stream/plan_ir.py nodes build into executors
here, so any expressible plan runs on any worker) or by NAME from the
legacy ``FRAGMENTS`` registry (``deploy``, kept for the hand-tuned q8
demo fragments).

Run as a process:  python -m risingwave_tpu.cluster.worker --store DIR
(prints one JSON line {"control_port": N, "exchange_port": N}).
"""

from __future__ import annotations

import asyncio
import json
from typing import Dict, Optional

from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.dispatch import Output, SimpleDispatcher
from risingwave_tpu.stream.exchange import channel_for_test
from risingwave_tpu.stream.message import (
    Barrier, BarrierKind, PauseMutation, ResumeMutation, StopMutation,
)
from risingwave_tpu.stream.remote import ExchangeServer


def _make_nexmark_source(w: "WorkerServer", p: dict, table_type: str):
    """Shared source wiring for nexmark fragments: reader + barrier
    channel + split-offset state + SourceExecutor."""
    from risingwave_tpu.common.types import Interval
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig, NexmarkSplitReader,
    )
    from risingwave_tpu.frontend.planner import SPLIT_STATE_SCHEMA
    from risingwave_tpu.stream.executors.source import SourceExecutor

    cfg = NexmarkConfig(table_type=table_type,
                        event_num=int(p["event_num"]),
                        max_chunk_size=int(p.get("chunk", 512)))
    reader = NexmarkSplitReader(cfg)
    tx, rx = channel_for_test()
    split = StateTable(int(p["split_table_id"]), SPLIT_STATE_SCHEMA,
                       [0], w.store)
    w.local.register_sender(int(p["actor_id"]), tx)
    src = SourceExecutor(reader, rx, split, actor_id=int(p["actor_id"]),
                         rate_limit_chunks_per_barrier=int(
                             p.get("rate_limit", 4)),
                         min_chunks_per_barrier=p.get("min_chunks"))
    window = Interval(usecs=int(p.get("window_usecs", 10_000_000)))
    return src, window


def _fragment_q8_person(w: "WorkerServer", p: dict):
    """person source → project(id, name, starttime) → remote out."""
    from risingwave_tpu.common.types import DataType
    from risingwave_tpu.expr.expr import InputRef, tumble_start
    from risingwave_tpu.stream.executors.simple import ProjectExecutor

    src, window = _make_nexmark_source(w, p, "person")
    s = src.schema
    proj = ProjectExecutor(
        src,
        exprs=[InputRef(s.index_of("id"), DataType.INT64),
               InputRef(s.index_of("name"), DataType.VARCHAR),
               tumble_start(InputRef(s.index_of("date_time"),
                                     DataType.TIMESTAMP), window)],
        names=["id", "name", "starttime"])
    return src, proj


def _fragment_q8_auction_dedup(w: "WorkerServer", p: dict):
    """auction source → project → DEVICE dedup agg → project → remote.

    Stateful fragment: the dedup HashAgg's kernel + value-state table
    live on THIS worker — q8's two sides' state end up on different
    processes."""
    from risingwave_tpu.common.types import DataType
    from risingwave_tpu.expr.expr import InputRef, tumble_start
    from risingwave_tpu.ops.hash_agg import AggKind
    from risingwave_tpu.stream.executors.hash_agg import (
        AggCall, HashAggExecutor, agg_state_schema,
    )
    from risingwave_tpu.stream.executors.simple import ProjectExecutor

    src, window = _make_nexmark_source(w, p, "auction")
    s = src.schema
    proj = ProjectExecutor(
        src,
        exprs=[InputRef(s.index_of("seller"), DataType.INT64),
               tumble_start(InputRef(s.index_of("date_time"),
                                     DataType.TIMESTAMP), window)],
        names=["seller", "starttime"])
    calls = [AggCall(AggKind.COUNT)]
    sch, pk = agg_state_schema(proj.schema, [0, 1], calls)
    dedup = HashAggExecutor(
        proj, [0, 1], calls,
        StateTable(int(p["agg_table_id"]), sch, pk, w.store,
                   dist_key_indices=[0]),
        append_only=True,
        output_names=["seller", "starttime", "_cnt"])
    out = ProjectExecutor(
        dedup, exprs=[InputRef(0, DataType.INT64),
                      InputRef(1, DataType.TIMESTAMP)],
        names=["seller", "starttime"])
    return src, out


FRAGMENTS = {
    "q8_person": _fragment_q8_person,
    "q8_auction_dedup": _fragment_q8_auction_dedup,
}


class WorkerServer:
    """One worker process: control + exchange + actors + local store."""

    def __init__(self, store):
        self.store = store
        self.local = LocalBarrierManager()
        self.exchange = ExchangeServer()
        self.actors: Dict[int, Actor] = {}
        self.tasks: Dict[int, asyncio.Task] = {}
        self._control: Optional[asyncio.AbstractServer] = None
        self._stopping = asyncio.Event()

    async def serve(self, host: str = "127.0.0.1") -> dict:
        await self.exchange.serve(host, 0)
        self._control = await asyncio.start_server(
            self._handle_control, host, 0)
        return {"control_port":
                self._control.sockets[0].getsockname()[1],
                "exchange_port": self.exchange.port}

    # -- control protocol: one JSON object per line ----------------------
    async def _handle_control(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                cmd = json.loads(line)
                try:
                    reply = await self._dispatch(cmd)
                except BaseException as e:  # noqa: BLE001 — report,
                    # don't kill the control channel: the coordinator
                    # needs the REAL failure, not a closed socket
                    reply = {"ok": False, "error": repr(e)}
                writer.write((json.dumps(reply) + "\n").encode())
                await writer.drain()
                if cmd.get("cmd") == "stop":
                    self._stopping.set()
                    return
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, cmd: dict) -> dict:
        verb = cmd.get("cmd")
        if verb == "deploy":
            return await self._deploy(cmd)
        if verb == "deploy_plan":
            return await self._deploy_plan(cmd)
        if verb == "inject":
            return await self._inject(cmd)
        if verb == "ping":
            # heartbeat probe (cluster.rs heartbeat RPC): liveness +
            # a cheap resource summary for the membership table
            return {"ok": True, "info": {"actors": len(self.actors)}}
        if verb == "stop":
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {verb!r}"}

    def _spawn_actor(self, actor_id: int, down_actor: Optional[int],
                     consumer) -> dict:
        """Shared deploy tail: exchange edge + actor + spawn (one
        copy — both deploy verbs must wire actors identically).
        down_actor=None: terminal fragment (e.g. a materialize) —
        no exchange edge; an edge nobody consumes would buffer
        chunks until the credit window blocks the actor."""
        dispatchers = []
        if down_actor is not None:
            out = self.exchange.register_edge(actor_id, down_actor)
            dispatchers = [SimpleDispatcher(Output(down_actor, out))]
        actor = Actor(actor_id, consumer, dispatchers=dispatchers,
                      barrier_manager=self.local)
        self.actors[actor_id] = actor
        self.local.set_expected_actors(list(self.actors))
        self.tasks[actor_id] = actor.spawn()
        return {"ok": True, "actor_id": actor_id}

    def _guarded_spawn(self, actor_id: int,
                       down_actor: Optional[int],
                       build, what: str) -> dict:
        """Shared deploy guard (one copy — both deploy verbs must
        fail identically): refuse duplicate actor ids BEFORE anything
        registers (the failure-path drop_actor would otherwise pop a
        LIVE actor's barrier sender along with the half-built one),
        and unwind the sender a failed build registered — an
        undrained bounded barrier channel wedges injection."""
        if actor_id in self.actors:
            return {"ok": False,
                    "error": f"actor {actor_id} already deployed"}
        try:
            consumer = build()
            return self._spawn_actor(actor_id, down_actor, consumer)
        except BaseException as e:     # noqa: BLE001 — report upstream
            self.local.drop_actor(actor_id)
            return {"ok": False, "error": f"{what} failed: {e}"}

    async def _deploy_plan(self, cmd: dict) -> dict:
        """Materialize a SHIPPED plan-IR fragment (from_proto/ analog):
        the coordinator sends the node tree over the control channel
        and this worker builds + spawns it — no per-query fragment
        registry, any plan the IR expresses deploys anywhere.

        The fragment's actor id comes from the PLAN's source node (one
        source of truth — a divergent params id would register the
        barrier sender under a key the stop path never drops). A build
        failure after sender registration unregisters it: an undrained
        barrier channel would wedge every later injection."""
        from risingwave_tpu.stream.plan_ir import build_fragment

        plan = cmd["plan"]
        sources = [n for n in plan if n.get("op") == "source"]
        remote_fed = any(n.get("op") == "remote_input" for n in plan)
        if len(sources) > 1 or (not sources and not remote_fed):
            return {"ok": False,
                    "error": "plan must have exactly one source node "
                             "or be fed by remote_input nodes"}
        try:
            # validate EVERYTHING that could fail before building:
            # build_fragment registers the source's barrier sender,
            # and a post-build failure would leave it undrained.
            # Terminal fragments (no exchange edge) must say so with
            # an EXPLICIT down_actor=None — a merely omitted key is a
            # wiring typo that would otherwise deploy ok and then
            # starve the downstream actor with no diagnostic
            raw_down = cmd["params"]["down_actor"]
            down_actor = None if raw_down is None else int(raw_down)
        except (KeyError, TypeError, ValueError) as e:
            return {"ok": False, "error": f"bad down_actor: {e}"}
        sent = cmd["params"].get("actor_id")
        if sources:
            actor_id = int(sources[0]["actor_id"])
            if sent is not None and int(sent) != actor_id:
                # the PLAN is the source of truth; silently deploying
                # under a different id than the caller thinks would
                # wedge its stop/tracking path with no diagnostic
                return {"ok": False,
                        "error": f"params actor_id {sent} != plan "
                                 f"source actor_id {actor_id}"}
        elif sent is None:
            return {"ok": False,
                    "error": "a remote-fed plan needs params "
                             "actor_id (no source node carries one)"}
        else:
            actor_id = int(sent)
        return self._guarded_spawn(
            actor_id, down_actor,
            lambda: build_fragment(plan, self.store, self.local,
                                   channel_for_test,
                                   actor_id=actor_id)[1],
            "plan build")

    async def _deploy(self, cmd: dict) -> dict:
        frag = FRAGMENTS[cmd["fragment"]]
        p = cmd["params"]
        return self._guarded_spawn(
            int(p["actor_id"]), int(p["down_actor"]),
            lambda: frag(self, p)[1],   # fragment registers its sender
            "deploy")

    async def _inject(self, cmd: dict) -> dict:
        pair = EpochPair(Epoch(int(cmd["curr"])),
                         Epoch(int(cmd["prev"])))
        kind = BarrierKind(cmd["kind"])
        mutation = None
        m = cmd.get("mutation")
        if m:
            if m["type"] == "stop":
                mutation = StopMutation(frozenset(m["actors"]))
            elif m["type"] == "pause":
                mutation = PauseMutation()
            elif m["type"] == "resume":
                mutation = ResumeMutation()
        barrier = Barrier(pair, kind, mutation)
        await self.local.send_barrier(barrier)
        collected = await self.local.await_epoch_complete(
            pair.curr.value)
        # the worker may have committed AHEAD of the coordinator (crash
        # between worker sync and coordinator commit): sealing an older
        # epoch again must be a no-op, not an assertion failure
        if pair.prev.value > self.store.committed_epoch():
            self.store.seal_epoch(pair.prev.value, kind.is_checkpoint)
            if kind.is_checkpoint:
                self.store.sync(pair.prev.value)
        # stopped actors are gone after this barrier
        if isinstance(mutation, StopMutation):
            for aid in list(self.actors):
                if aid in mutation.actors:
                    t = self.tasks.pop(aid, None)
                    if t is not None:
                        await t
                    self.actors.pop(aid, None)
                    self.local.drop_actor(aid)
            self.local.set_expected_actors(list(self.actors))
        for aid, a in self.actors.items():
            if a.failure is not None:
                return {"ok": False, "error": repr(a.failure)}
        return {"ok": True, "collected": collected is not None,
                "committed": pair.prev.value}

    async def run_until_stopped(self) -> None:
        await self._stopping.wait()
        await self.exchange.close()
        if self._control is not None:
            self._control.close()
            await self._control.wait_closed()


def main(argv=None) -> None:
    import argparse
    import os

    # honor JAX_PLATFORMS=cpu even where a sitecustomize rewrites the
    # platform list at interpreter start (a worker pinned to CPU must
    # not block on a wedged accelerator tunnel)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    ap = argparse.ArgumentParser()
    ap.add_argument("--store", required=True,
                    help="object-store directory for this worker's "
                         "hummock namespace")
    args = ap.parse_args(argv)

    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    async def amain():
        store = HummockLite(LocalFsObjectStore(args.store))
        w = WorkerServer(store)
        ports = await w.serve()
        print(json.dumps(ports), flush=True)
        await w.run_until_stopped()

    asyncio.run(amain())


if __name__ == "__main__":
    main()
