"""Worker node: actors + exchange server + control channel.

Reference parity: the compute node (src/compute/src/server.rs:85) —
hosts actors, serves its outputs over the exchange (stream/remote.py),
executes barrier injections from the coordinator and reports collection
(stream_service.proto InjectBarrier/BarrierComplete), owns a local
state-store namespace whose checkpoints commit at the SAME epochs the
coordinator drives, so a recovering cluster resumes consistently from
the coordinator's committed epoch.

Fragments deploy by SHIPPED PLAN IR only (``deploy_plan`` — the
stream_plan.proto analog; stream/plan_ir.py nodes build into executors
here, so any expressible plan runs on any worker). Each deployed actor
may fan out through a dispatcher spec — simple / broadcast / hash with
an explicit vnode→downstream-actor mapping (dispatch.rs:582; the
coordinator's scheduler computes the mapping like
meta/stream/stream_graph/schedule.rs:195-251 assigns vnode bitmaps).

The batch data plane for distributed SELECT: ``scan_table`` streams a
table's committed rows back over control (ExchangeService.GetData +
RowSeqScan over the local store, task_service.proto:114), and
``ingest_table`` bulk-loads rows at a fresh epoch (the state-migration
half of a cross-worker reschedule).

Run as a process:  python -m risingwave_tpu.cluster.worker --store DIR
(prints one JSON line {"control_port": N, "exchange_port": N}).
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Dict, List, Optional

from risingwave_tpu.cluster.coordinator import (
    CONTROL_LINE_LIMIT, CONTROL_PAGE_BYTES,
)
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.dispatch import (
    BroadcastDispatcher, HashDispatcher, Output, SimpleDispatcher,
)
from risingwave_tpu.stream.exchange import channel_for_test
from risingwave_tpu.stream.message import (
    Barrier, BarrierKind, PauseMutation, ResumeMutation, StopMutation,
)
from risingwave_tpu.stream.remote import ExchangeServer


class WorkerServer:
    """One worker process: control + exchange + actors + local store."""

    def __init__(self, store):
        self.store = store
        self.local = LocalBarrierManager()
        self.exchange = ExchangeServer()
        self.actors: Dict[int, Actor] = {}
        self.tasks: Dict[int, asyncio.Task] = {}
        self._control: Optional[asyncio.AbstractServer] = None
        self._stopping = asyncio.Event()
        # per-domain stamp of the last non-mutation inject handled
        # here: successive stamps bound the barrier interval the
        # worker-side bottleneck walk observes (the coordinator hosts
        # no monitored actors on a distributed session — the walker
        # must run where the chains run)
        self._domain_stamp: Dict[str, float] = {}

    async def serve(self, host: str = "127.0.0.1") -> dict:
        await self.exchange.serve(host, 0)
        self._control = await asyncio.start_server(
            self._handle_control, host, 0, limit=CONTROL_LINE_LIMIT)
        return {"control_port":
                self._control.sockets[0].getsockname()[1],
                "exchange_port": self.exchange.port}

    # -- control protocol: one JSON object per line ----------------------
    async def _handle_control(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                cmd = json.loads(line)
                try:
                    reply = await self._dispatch(cmd)
                except BaseException as e:  # noqa: BLE001 — report,
                    # don't kill the control channel: the coordinator
                    # needs the REAL failure, not a closed socket
                    reply = {"ok": False, "error": repr(e)}
                writer.write((json.dumps(reply) + "\n").encode())
                await writer.drain()
                if cmd.get("cmd") == "stop":
                    self._stopping.set()
                    return
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, cmd: dict) -> dict:
        verb = cmd.get("cmd")
        # chaos seam: delay (sleep spec) or fail (raise spec) one
        # control RPC by verb — how the harness injects an RPC timeout
        # without killing the worker
        from risingwave_tpu.utils.failpoint import fail_point
        fail_point(f"worker.rpc.{verb}")
        if verb == "deploy_plan":
            return await self._deploy_plan(cmd)
        if verb == "inject":
            return await self._inject(cmd)
        if verb == "scan_table":
            return self._scan_table(cmd)
        if verb == "ingest_table":
            return self._ingest_table(cmd)
        if verb == "seal_sync":
            # cross-domain aligned checkpoint (ISSUE 13): the
            # coordinator pushes the write floor once EVERY domain of
            # the round collected — seal + stage-sync everything at or
            # below it in one absolute-state (idempotent) step; the
            # commit decision still pipelines on the next barrier's
            # "committed" field
            epoch = int(cmd["epoch"])
            sealed = max(self.store.committed_epoch(),
                         getattr(self.store, "_sealed_epoch", 0))
            if epoch > sealed:
                self.store.seal_epoch(epoch, True)
            self.store.sync(epoch)
            return {"ok": True,
                    "committed": self.store.committed_epoch()}
        if verb == "recover_store":
            # recovery handshake: adopt everything the coordinator
            # committed, discard the half-epoch a crash may have left
            # staged (recovery.rs: the committed epoch is the truth)
            epoch = int(cmd["epoch"])
            dropped = 0
            if getattr(self.store, "two_phase", False):
                dropped = self.store.discard_staged_above(epoch)
                self.store.commit_through(epoch)
            return {"ok": True, "dropped": dropped,
                    "committed": self.store.committed_epoch()}
        if verb == "reset":
            return await self._reset()
        if verb == "arm_failpoints":
            # live chaos injection: arm/disarm JSON-able dict specs in
            # THIS process (the env path only covers boot time)
            from risingwave_tpu.utils.failpoint import arm_specs
            return {"ok": True,
                    "armed": arm_specs(cmd.get("points") or {})}
        if verb == "metrics":
            # this process's Prometheus exposition — how tests and
            # tooling observe worker-side absorption counters
            # (object_store_retry_total lives here, not on the
            # coordinator)
            from risingwave_tpu.utils.metrics import GLOBAL
            return {"ok": True, "text": GLOBAL.render()}
        if verb == "set_trace":
            from risingwave_tpu.utils import spans as _spans
            _spans.set_enabled(bool(cmd.get("on", True)))
            return {"ok": True}
        if verb == "set_ledger":
            from risingwave_tpu.utils import ledger as _ledger
            _ledger.set_enabled(bool(cmd.get("on", True)))
            return {"ok": True}
        if verb == "drain_trace":
            # pop this process's recorded spans for the coordinator to
            # merge (tagged with the worker slot on the other side)
            from risingwave_tpu.utils.spans import EPOCH_TRACER
            return {"ok": True, "spans": EPOCH_TRACER.drain_dicts()}
        if verb == "drain_ledger":
            # pop this process's open phase-ledger accumulators —
            # workers never seal (the coordinator owns the barrier
            # interval); the other side merges them into its records
            from risingwave_tpu.utils.ledger import LEDGER
            return {"ok": True, "epochs": LEDGER.drain_dicts()}
        if verb == "signals":
            # autoscaler signal snapshot (ISSUE 15/16): this process's
            # utilization tricolor + bottleneck-walker state, plus the
            # attribution surfaces (state topology + hot-key sketches
            # as snapshots, per-MV cost books as a true drain — the
            # coordinator owns the merged totals), merged
            # coordinator-side by Cluster.drain_signals
            from risingwave_tpu.state.topology import TOPOLOGY
            from risingwave_tpu.stream.bottleneck import BOTTLENECKS
            from risingwave_tpu.stream.costs import COSTS
            from risingwave_tpu.stream.hotkeys import HOTKEYS
            from risingwave_tpu.stream.monitor import UTILIZATION
            out = {"ok": True,
                   "utilization": UTILIZATION.rows(),
                   "bottlenecks": BOTTLENECKS.rows(),
                   "mv_costs": COSTS.drain_dict()}
            if not cmd.get("light"):
                # the per-vnode topology snapshot walks the per-key
                # map — serve it only to query-driven drains, never
                # the per-tick heartbeat (light=True)
                out["topology"] = TOPOLOGY.drain_rows()
                out["hot_keys"] = HOTKEYS.drain_rows()
            return out
        if verb == "set_costs":
            from risingwave_tpu.stream import costs as _costs
            _costs.set_enabled(bool(cmd.get("on", True)))
            return {"ok": True}
        if verb == "drain_freshness":
            # pop this process's raw freshness parts (ingest hwms,
            # epoch frontiers, visibility events) — the coordinator
            # joins source and materialize fragments that landed on
            # different workers into one per-MV lag series
            from risingwave_tpu.stream.freshness import FRESHNESS
            return {"ok": True, "parts": FRESHNESS.drain_dict()}
        if verb == "awaits":
            # wedge diagnostics: where every registered coroutine in
            # THIS process is parked (the PR-1 AwaitRegistry) plus the
            # local barrier manager's open epochs — how a coordinator
            # names the actor holding a barrier open on a live worker
            # instead of guessing from the outside
            from risingwave_tpu.utils.trace import GLOBAL_AWAITS
            local = self.local
            return {"ok": True, "text": GLOBAL_AWAITS.dump(),
                    "actors": sorted(self.actors),
                    "open_epochs": {
                        f"{e:#x}": sorted(
                            local._collected.get(e, ()))
                        for e in getattr(local, "_complete", {})
                        if not local._complete[e].is_set()}}
        if verb == "set_compaction":
            # absolute-state toggle: inline (commits compact in place)
            # vs dedicated (commits never compact; version deltas land
            # via compact_apply below)
            mode = str(cmd.get("mode", "inline"))
            self.store.compaction_mode = mode
            return {"ok": True, "mode": mode}
        if verb == "level_snapshot":
            # pure read: per-level topology for the CompactionManager's
            # pickers (L0 run count, sizes, tombstone density) + the
            # ids frozen under in-flight tasks
            return {"ok": True, "snapshot": self.store.level_snapshot()}
        if verb == "compact_reserve":
            # freeze a task's inputs + burn it a durable output-id
            # block; a ValueError (inputs gone / already reserved) is
            # an expected conflict the manager skips, not a fault
            grant = self.store.reserve_task(
                [int(i) for i in cmd["inputs"]],
                int(cmd.get("id_block", 16)))
            return {"ok": True, "grant": grant}
        if verb == "compact_apply":
            # compare-and-commit version delta: swap exactly the
            # reserved inputs for the compactor's outputs
            r = self.store.apply_version_delta(
                [int(i) for i in cmd["inputs"]], cmd["outputs"])
            return {"ok": True, **r}
        if verb == "compact_abort":
            self.store.abort_task(
                [int(i) for i in cmd["inputs"]],
                [int(i) for i in cmd.get("outputs") or []])
            return {"ok": True}
        if verb == "ping":
            # heartbeat probe (cluster.rs heartbeat RPC): liveness +
            # a cheap resource summary for the membership table (actor
            # failures ride along so a dead-epoch diagnosis can name
            # the culprit without waiting for the next inject)
            return {"ok": True, "info": {
                "actors": len(self.actors),
                "failures": {str(aid): repr(a.failure)
                             for aid, a in self.actors.items()
                             if a.failure is not None}}}
        if verb == "stop":
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {verb!r}"}

    async def _reset(self) -> dict:
        """Supervised-recovery rung 2 for a LIVE worker: drop every
        actor in place (no stop barriers — the barrier plane is the
        thing that failed), release the exchange plane, and present a
        fresh LocalBarrierManager. The process — and its warm jit
        caches — survives, which is exactly what makes respawn cheaper
        than full recovery. Staged store state is NOT touched here:
        the coordinator's ``recover_store`` handshake that follows is
        the single source of truth for what rolls back."""
        n = len(self.actors)
        for t in self.tasks.values():
            t.cancel()
        if self.tasks:
            await asyncio.gather(*self.tasks.values(),
                                 return_exceptions=True)
        self.actors.clear()
        self.tasks.clear()
        self._domain_stamp.clear()
        old = self.local
        self.local = LocalBarrierManager()
        # wake any control handler stuck awaiting an epoch on the old
        # plane (e.g. a wedged inject on a torn connection): resolving
        # its await with the failure beats leaking the coroutine
        old.notify_failure(-1, RuntimeError(
            "worker reset (supervised recovery)"))
        self.exchange.reset_edges()
        return {"ok": True, "dropped_actors": n}

    # -- exchange fan-out -------------------------------------------------
    def _make_dispatchers(self, actor_id: int, outputs: List[int],
                          dispatch: Optional[dict]) -> list:
        """Downstream edges on THIS worker's exchange server; remote
        peers connect in and pull (exchange_service.rs). The spec picks
        the dispatcher (dispatch.rs:343): simple needs exactly one
        output; hash carries dist keys + an explicit vnode mapping."""
        outs = [Output(d, self.exchange.register_edge(actor_id, d))
                for d in outputs]
        if not outs:
            return []
        spec = dispatch or {"type": "simple"}
        typ = spec.get("type", "simple")
        if typ == "simple":
            if len(outs) != 1:
                raise ValueError(
                    f"simple dispatch needs 1 output, got {len(outs)}")
            return [SimpleDispatcher(outs[0])]
        if typ == "broadcast":
            return [BroadcastDispatcher(outs)]
        if typ == "hash":
            from risingwave_tpu.common.hash import VnodeMapping
            import numpy as np
            keys = [int(i) for i in spec["keys"]]
            raw = spec.get("mapping")
            mapping = (VnodeMapping(np.asarray(raw, dtype=np.int32))
                       if raw is not None else None)
            return [HashDispatcher(outs, keys, mapping)]
        raise ValueError(f"unknown dispatch type {typ!r}")

    def _spawn_actor(self, actor_id: int, outputs: List[int],
                     dispatch: Optional[dict], consumer,
                     fragment: str = "") -> dict:
        """Shared deploy tail: exchange edges + actor + spawn.
        outputs=[]: terminal fragment (e.g. a materialize) — no
        exchange edge; an edge nobody consumes would buffer chunks
        until the credit window blocks the actor."""
        from risingwave_tpu.stream.monitor import install_monitoring
        dispatchers = self._make_dispatchers(actor_id, outputs, dispatch)
        # worker-side instrumentation feeds THIS process's registry
        # (a worker-local scrape); the coordinator's rw_actor_metrics
        # only sees coordinator-process actors — cross-process metric
        # aggregation is future work
        consumer = install_monitoring(consumer, fragment=fragment,
                                      actor_id=actor_id)
        actor = Actor(actor_id, consumer, dispatchers=dispatchers,
                      barrier_manager=self.local, fragment=fragment)
        self.actors[actor_id] = actor
        self.local.set_expected_actors(list(self.actors))
        self.tasks[actor_id] = actor.spawn()
        return {"ok": True, "actor_id": actor_id}

    async def _deploy_plan(self, cmd: dict) -> dict:
        """Materialize a SHIPPED plan-IR fragment (from_proto/ analog):
        the coordinator sends the node tree over the control channel
        and this worker builds + spawns it — no per-query fragment
        registry, any plan the IR expresses deploys anywhere.

        The fragment's actor id comes from the PLAN's source node (one
        source of truth — a divergent params id would register the
        barrier sender under a key the stop path never drops). A build
        failure after sender registration unregisters it: an undrained
        barrier channel would wedge every later injection."""
        from risingwave_tpu.stream.plan_ir import build_fragment

        plan = cmd["plan"]
        params = cmd["params"]
        sources = [n for n in plan if n.get("op") == "source"]
        remote_fed = any(n.get("op") == "remote_input" for n in plan)
        if len(sources) > 1 or (not sources and not remote_fed):
            return {"ok": False,
                    "error": "plan must have exactly one source node "
                             "or be fed by remote_input nodes"}
        try:
            # validate EVERYTHING that could fail before building:
            # build_fragment registers the source's barrier sender,
            # and a post-build failure would leave it undrained.
            # Terminal fragments (no exchange edge) must say so with
            # an EXPLICIT outputs=[] / down_actor=None — a merely
            # omitted key is a wiring typo that would otherwise deploy
            # ok and then starve the downstream actor silently
            if "outputs" in params:
                outputs = [int(o) for o in params["outputs"]]
            else:
                raw_down = params["down_actor"]
                outputs = [] if raw_down is None else [int(raw_down)]
            dispatch = params.get("dispatch")
            if dispatch is not None and dispatch.get("type") == "hash":
                _ = [int(i) for i in dispatch["keys"]]
        except (KeyError, TypeError, ValueError) as e:
            return {"ok": False, "error": f"bad output spec: {e}"}
        sent = params.get("actor_id")
        if sources:
            actor_id = int(sources[0]["actor_id"])
            if sent is not None and int(sent) != actor_id:
                # the PLAN is the source of truth; silently deploying
                # under a different id than the caller thinks would
                # wedge its stop/tracking path with no diagnostic
                return {"ok": False,
                        "error": f"params actor_id {sent} != plan "
                                 f"source actor_id {actor_id}"}
        elif sent is None:
            return {"ok": False,
                    "error": "a remote-fed plan needs params "
                             "actor_id (no source node carries one)"}
        else:
            actor_id = int(sent)
        if actor_id in self.actors:
            return {"ok": False,
                    "error": f"actor {actor_id} already deployed"}
        try:
            consumer = build_fragment(plan, self.store, self.local,
                                      channel_for_test,
                                      actor_id=actor_id)[1]
            return self._spawn_actor(
                actor_id, outputs, dispatch, consumer,
                fragment=str(params.get("job") or f"actor-{actor_id}"))
        except BaseException as e:     # noqa: BLE001 — report upstream
            self.local.drop_actor(actor_id)
            return {"ok": False, "error": f"plan build failed: {e}"}

    _PAGE_BYTES = CONTROL_PAGE_BYTES

    # -- batch data plane -------------------------------------------------
    def _scan_table(self, cmd: dict) -> dict:
        """Stream one table's committed rows back to the coordinator
        (RowSeqScan over the local store + GetData, collapsed to the
        control channel). Rows are value-codec encoded — the
        coordinator holds the schema; this side needs none. PAGED:
        ``after`` (hex key, exclusive) resumes a scan and the reply
        stops past a byte budget with ``done=False`` — one giant
        table must not overflow the JSON-line framing."""
        from risingwave_tpu.storage.value_codec import encode_row

        tid = int(cmd["table_id"])
        epoch = cmd.get("epoch")
        epoch = (self.store.committed_epoch() if epoch is None
                 else int(epoch))
        after = (bytes.fromhex(cmd["after"])
                 if cmd.get("after") else None)
        rows = []
        nbytes = 0
        done = True
        # resume at the store level (start is inclusive; after+\x00 is
        # the exclusive successor) so a P-page scan stays O(N), not
        # O(P*N); the guard below keeps correctness if a store ever
        # ignores start
        start = None if after is None else after + b"\x00"
        for k, v in self.store.iter(tid, epoch, start=start):
            if after is not None and k <= after:
                continue
            kx, vx = k.hex(), encode_row(tuple(v)).hex()
            rows.append([kx, vx])
            nbytes += len(kx) + len(vx)
            if nbytes >= self._PAGE_BYTES:
                done = False
                break
        return {"ok": True, "epoch": epoch, "rows": rows,
                "done": done}

    def _ingest_table(self, cmd: dict) -> dict:
        """Bulk-load rows into a table at a fresh sealed+synced epoch —
        the receiving half of cross-worker state migration (the
        reference moves no state because storage is shared; with
        per-worker namespaces the reschedule barrier ships it)."""
        from risingwave_tpu.storage.value_codec import decode_row

        tid = int(cmd["table_id"])
        batch = [(bytes.fromhex(k),
                  None if r is None else decode_row(bytes.fromhex(r)))
                 for k, r in cmd["rows"]]
        # min_epoch: the coordinator's last-injected epoch — sealing
        # at or below an in-flight barrier's curr would make OTHER
        # jobs' buffered flushes at that epoch fail the sealed guard
        epoch = max(self.store.committed_epoch(),
                    getattr(self.store, "_sealed_epoch", 0),
                    int(cmd.get("min_epoch") or 0)) + 1
        self.store.ingest_batch(tid, batch, epoch)
        self.store.seal_epoch(epoch, True)
        self.store.sync(epoch)
        if getattr(self.store, "two_phase", False):
            # a coordinator-driven bulk load IS the commit decision:
            # leaving it staged would let a recovery in the next two
            # barriers discard freshly-migrated state
            self.store.commit_through(epoch)
        return {"ok": True, "rows": len(batch), "epoch": epoch}

    async def _inject(self, cmd: dict) -> dict:
        pair = EpochPair(Epoch(int(cmd["curr"])),
                         Epoch(int(cmd["prev"])))
        kind = BarrierKind(cmd["kind"])
        mutation = None
        m = cmd.get("mutation")
        if m:
            if m["type"] == "stop":
                mutation = StopMutation(frozenset(m["actors"]))
            elif m["type"] == "pause":
                mutation = PauseMutation()
            elif m["type"] == "resume":
                mutation = ResumeMutation()
        barrier = Barrier(pair, kind, mutation)
        from risingwave_tpu.utils import spans as _spans
        _spans.set_current_epoch(pair.curr.value)
        if _spans.enabled():
            # worker-side inject marker, parented to the coordinator's
            # inject span when the injection shipped one: every span
            # this process records for the epoch links under it
            parent = (cmd.get("trace") or {}).get("span")
            wroot = _spans.EPOCH_TRACER.record(
                "barrier.inject.worker", "barrier",
                epoch=pair.curr.value, parent=parent,
                kind=kind.value)
            _spans.EPOCH_TRACER.set_root(pair.curr.value, wroot)
        actors = cmd.get("actors")
        if "seal" in cmd:
            # domain-protocol marker: a coordinator-side domain merge
            # can re-anchor live chains on THIS worker monotonely —
            # commit() must accept prev > curr from here on
            from risingwave_tpu.state.state_table import (
                allow_monotone_reanchor,
            )
            allow_monotone_reanchor(True)
        if actors is None:
            await self.local.send_barrier(barrier)
        else:
            # barrier-domain frame (ISSUE 13): the barrier flows only
            # through this domain's actors on this worker; sibling
            # domains' actors neither receive nor block it. An empty
            # intersection collects trivially — the worker simply
            # hosts none of the domain's fragments.
            acts = {int(a) for a in actors}
            await self.local.send_barrier(
                barrier, sender_ids=sorted(acts),
                expected=[a for a in self.actors if a in acts])
        collected = await self.local.await_epoch_complete(
            pair.curr.value)
        sealed = max(self.store.committed_epoch(),
                     getattr(self.store, "_sealed_epoch", 0))
        if "seal" in cmd:
            # domain-plane protocol: per-domain prevs interleave
            # globally, so the worker fences only to the cross-domain
            # write floor the coordinator computed; durability arrives
            # via the aligned seal_sync push, never inline here
            s = int(cmd.get("seal") or 0)
            if s > sealed:
                self.store.seal_epoch(s, kind.is_checkpoint)
        elif pair.prev.value > sealed:
            # legacy global-lockstep protocol: seal+stage the epoch
            # that ENDED. The guard makes re-injection after recovery
            # a no-op rather than an assertion failure.
            self.store.seal_epoch(pair.prev.value, kind.is_checkpoint)
            if kind.is_checkpoint:
                self.store.sync(pair.prev.value)
        if getattr(self.store, "two_phase", False):
            # the coordinator's commit decision rides on this barrier
            # (HummockManager::commit_epoch pipelined one barrier
            # behind). Absent — a legacy driver — self-commit through
            # the epoch just SYNCED, and only on checkpoint barriers:
            # committing a merely-sealed epoch would write a durable
            # version that claims data still sitting in the imms
            committed = cmd.get("committed")
            if committed is not None:
                self.store.commit_through(int(committed))
            elif kind.is_checkpoint:
                self.store.commit_through(pair.prev.value)
        # worker-side bottleneck walk (ISSUE 15): the tricolor rows
        # this barrier just published decompose THIS process's chains;
        # the inject frame's domain name + actor filter scope the walk,
        # and successive inject stamps bound the interval. Mutation
        # barriers (deploy/stop/reschedule) do topology work, not
        # epoch work — they neither tick nor reset the streaks.
        dom = cmd.get("domain")
        if dom is not None:
            from risingwave_tpu.stream import monitor as _monitor
            now = time.monotonic()
            last = self._domain_stamp.get(dom)
            self._domain_stamp[dom] = now
            if (mutation is None and last is not None
                    and _monitor.TRICOLOR):
                from risingwave_tpu.stream.bottleneck import BOTTLENECKS
                BOTTLENECKS.observe(
                    domain=dom, epoch=pair.curr.value,
                    interval_s=now - last,
                    actors={int(a) for a in actors}
                    if actors is not None else None)
        # stopped actors are gone after this barrier
        if isinstance(mutation, StopMutation):
            for aid in list(self.actors):
                if aid in mutation.actors:
                    t = self.tasks.pop(aid, None)
                    if t is not None:
                        await t
                    self.actors.pop(aid, None)
                    self.local.drop_actor(aid)
            self.local.set_expected_actors(list(self.actors))
        for aid, a in self.actors.items():
            if a.failure is not None:
                return {"ok": False,
                        "error": f"actor {aid} ({a.fragment}): "
                                 f"{a.failure!r}"}
        return {"ok": True, "collected": collected is not None,
                "committed": pair.prev.value}

    async def run_until_stopped(self) -> None:
        await self._stopping.wait()
        await self.exchange.close()
        if self._control is not None:
            self._control.close()
            await self._control.wait_closed()


class CompactorServer:
    """Dedicated compactor role (``--role compactor``): a heartbeat-
    leased subprocess that executes compaction merges against worker
    object-store namespaces, OFF every serving path. It hosts no
    actors and owns no store of its own — each ``compact_task`` names
    the namespace directory and the frozen task; the merge runs on a
    thread so the control loop keeps answering pings mid-task.
    Compactor death mid-task surfaces as a torn control channel (or a
    lease expiry) and the manager requeues the task — the merge wrote
    only into its reserved id block, so a half-finished task leaves
    nothing a vacuum pass cannot reclaim."""

    def __init__(self) -> None:
        self._control: Optional[asyncio.AbstractServer] = None
        self._stopping = asyncio.Event()
        self._running = 0            # tasks in flight (ping visibility)
        self._done = 0

    async def serve(self, host: str = "127.0.0.1") -> dict:
        self._control = await asyncio.start_server(
            self._handle_control, host, 0, limit=CONTROL_LINE_LIMIT)
        return {"control_port":
                self._control.sockets[0].getsockname()[1],
                "exchange_port": 0}

    async def _handle_control(self, reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                cmd = json.loads(line)
                try:
                    reply = await self._dispatch(cmd)
                except BaseException as e:  # noqa: BLE001 — report
                    reply = {"ok": False, "error": repr(e)}
                writer.write((json.dumps(reply) + "\n").encode())
                await writer.drain()
                if cmd.get("cmd") == "stop":
                    self._stopping.set()
                    return
        except (ConnectionResetError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    async def _dispatch(self, cmd: dict) -> dict:
        verb = cmd.get("cmd")
        from risingwave_tpu.utils.failpoint import fail_point
        fail_point(f"compactor.rpc.{verb}")
        if verb == "ping":
            return {"ok": True, "info": {"role": "compactor",
                                         "running": self._running,
                                         "done": self._done}}
        if verb == "compact_task":
            return await self._compact_task(cmd)
        if verb == "arm_failpoints":
            from risingwave_tpu.utils.failpoint import arm_specs
            return {"ok": True,
                    "armed": arm_specs(cmd.get("points") or {})}
        if verb == "metrics":
            from risingwave_tpu.utils.metrics import GLOBAL
            return {"ok": True, "text": GLOBAL.render()}
        if verb == "stop":
            return {"ok": True}
        return {"ok": False, "error": f"unknown cmd {verb!r}"}

    async def _compact_task(self, cmd: dict) -> dict:
        from risingwave_tpu.storage.compactor import execute_task
        from risingwave_tpu.storage.object_store import (
            LocalFsObjectStore, RetryingObjectStore,
        )
        store = RetryingObjectStore(LocalFsObjectStore(cmd["store"]))
        self._running += 1
        try:
            result = await asyncio.to_thread(
                execute_task, store, cmd["task"])
        finally:
            self._running -= 1
        self._done += 1
        return {"ok": True, **result}

    async def run_until_stopped(self) -> None:
        await self._stopping.wait()
        if self._control is not None:
            self._control.close()
            await self._control.wait_closed()


def main(argv=None) -> None:
    import argparse
    import os

    # honor JAX_PLATFORMS=cpu even where a sitecustomize rewrites the
    # platform list at interpreter start (a worker pinned to CPU must
    # not block on a wedged accelerator tunnel)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")

    # chaos/trace tests arm sleep-spec failpoints in worker
    # subprocesses via the environment (utils/failpoint.py)
    from risingwave_tpu.utils.failpoint import arm_from_env
    arm_from_env()

    ap = argparse.ArgumentParser()
    ap.add_argument("--store", required=True,
                    help="object-store directory for this worker's "
                         "hummock namespace (compactor role: unused "
                         "default root — tasks name their namespace)")
    ap.add_argument("--role", default="worker",
                    choices=["worker", "compactor"],
                    help="worker: actors + local store; compactor: "
                         "dedicated off-path merge executor")
    args = ap.parse_args(argv)

    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import (
        LocalFsObjectStore, RetryingObjectStore,
    )

    async def amain():
        if args.role == "compactor":
            c = CompactorServer()
            ports = await c.serve()
            print(json.dumps(ports), flush=True)
            await c.run_until_stopped()
            return
        # transient-fault absorption at the bottom rung: a flaky
        # PUT/GET retries with jittered backoff inside the worker
        # before any error can fail a barrier round
        store = HummockLite(
            RetryingObjectStore(LocalFsObjectStore(args.store)),
            two_phase=True)
        w = WorkerServer(store)
        ports = await w.serve()
        print(json.dumps(ports), flush=True)
        await w.run_until_stopped()

    asyncio.run(amain())


if __name__ == "__main__":
    main()
