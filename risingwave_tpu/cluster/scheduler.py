"""Cluster: N workers + fragment scheduling + coordinated barriers.

Reference parity: GlobalStreamManager + the actor-graph scheduler
(src/meta/src/stream/stream_manager.rs:161,
src/meta/src/stream/stream_graph/schedule.rs:195-251 — fragments are
scheduled onto parallel units across compute nodes, hash fragments get
the 256-vnode bitmap split among their actors) and GlobalBarrierManager
fan-out (barrier/mod.rs:558 — one InjectBarrier per compute node,
collect-all, then HummockManager::commit_epoch). TPU re-design: each
worker slot owns a hummock namespace under one root; the coordinator
owns the BarrierLoop, pipelines its commit decision onto the next
barrier (two-phase worker stores), and recovery = restart every slot
over its namespace, replay the deployed jobs, resume from the
coordinator's committed epoch (barrier/recovery.rs:110 collapsed).
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from risingwave_tpu.cluster.coordinator import (
    Heartbeater, WorkerBarrierSender, WorkerClient, WorkerHandle,
)
from risingwave_tpu.frontend.fragmenter import Fragment, FragmentGraph
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.meta.supervisor import (
    ACTION_REQUEUE, ACTION_RESPAWN, ACTION_ROLLBACK,
    CAUSE_COMPACTOR_DEAD, CAUSE_RESCALE_FAILED, RecoveryEvent,
    RecoverySupervisor, trace_recovery_phase, trace_recovery_root,
)
from risingwave_tpu.stream.actor import LocalBarrierManager
from risingwave_tpu.stream.message import StopMutation
from risingwave_tpu.stream.plan_ir import remap_node_refs
from risingwave_tpu.utils.failpoint import fail_point

_PSEUDO_BASE = 1 << 20          # pseudo-actor ids for worker handles


class RescaleError(RuntimeError):
    """A guarded rescale failed. ``rolled_back=True`` means the prior
    topology (and state placement) was restored — the cluster is
    consistent and serving; False means the unwind itself failed (or
    the failure struck before any change, ``phase="stop"``, where the
    domain's health is unknown) and the supervised-recovery ladder
    owns what happens next. Either way the event is in rw_recovery."""

    def __init__(self, msg: str, phase: str, rolled_back: bool):
        super().__init__(msg)
        self.phase = phase
        self.rolled_back = rolled_back


class RescaleInProgressError(RuntimeError):
    """A topology change is already in flight for this cluster —
    concurrent rescales of one domain must serialize, never
    interleave (arxiv 1904.03800's concurrent-state discipline)."""


class _CoordEpochStore:
    """BarrierLoop's store shim: epochs COMMIT on the workers (staged
    SSTs adopted via commit_through); the coordinator only tracks the
    committed watermark — the HummockManager version counter without
    the SST bookkeeping."""

    def __init__(self, floor: int = 0):
        self._committed = floor

    def committed_epoch(self) -> int:
        return self._committed

    def seal_epoch(self, epoch: int, is_checkpoint: bool = True) -> None:
        pass

    def sync(self, epoch: int) -> None:
        self._committed = max(self._committed, epoch)


@dataclass
class JobDeployment:
    """One deployed streaming job: its fragment graph + placements.
    placements[fi] = [(actor_id, worker_slot), ...] per fragment.
    ``domain_keys`` are the job's barrier-domain reachability anchors
    (its source/MV dependency names — jobs sharing one align in a
    single domain; recorded so recovery rebuilds the same domains)."""

    name: str
    graph: FragmentGraph
    placements: List[List[tuple]] = field(default_factory=list)
    domain_keys: frozenset = frozenset()
    # fragment idx → per-actor-RANK partition lists (filelog sources):
    # the split/offsets contract — deploys stamp each source actor's
    # partition subset into its plan, rescales recompute it, and the
    # split-state handoff moves each split's offset row to its new
    # owner's namespace so reads resume exactly
    split_assignments: Dict[int, List[List[int]]] = \
        field(default_factory=dict)

    def actor_ids(self) -> List[int]:
        return [aid for frag in self.placements for aid, _slot in frag]


class Cluster:
    """Coordinator-side handle on N worker processes."""

    def __init__(self, root: str, n_workers: int = 2,
                 platform: str = "cpu",
                 barrier_timeout_s: Optional[float] = None,
                 supervisor: Optional[RecoverySupervisor] = None,
                 epoch_pipeline: bool = True):
        self.root = root
        self.n = n_workers
        self.platform = platform
        self.handles: List[Optional[WorkerHandle]] = [None] * n_workers
        self.clients: List[Optional[WorkerClient]] = [None] * n_workers
        self.jobs: Dict[str, JobDeployment] = {}
        self.local: Optional[LocalBarrierManager] = None
        self.loop = None        # BarrierLoop (off arm) or BarrierPlane
        self.store = _CoordEpochStore()
        # pipelined epochs (ISSUE 13): per-job barrier domains with
        # their own control connections per worker (two domains'
        # injects must not serialize behind one request-response
        # channel); off = the legacy single global loop, bit-identical
        self.epoch_pipeline = bool(epoch_pipeline)
        self._plane = None
        # domain → {"pids": [per-slot pseudo ids], "clients": [...]}
        self._domain_wiring: Dict[str, dict] = {}
        self._domain_seq = 0
        self._next_actor = 1000
        self._rr = 0                      # placement cursor
        # supervised recovery (meta/supervisor.py): classification +
        # storm gate; barrier_timeout_s arms wedged-barrier detection
        self.supervisor = supervisor or RecoverySupervisor()
        self.barrier_timeout_s = barrier_timeout_s
        # heartbeat-expiry detection (enable_liveness): lease-expired
        # slots feed the supervisor's dead set even while their
        # subprocess is technically alive (wedged, not exited)
        self._manager = None
        self._heartbeater: Optional[Heartbeater] = None
        self._expired_slots: Set[int] = set()
        self._wid_slot: Dict[int, int] = {}
        # topology-change serialization (ISSUE 15): one rescale/move at
        # a time per cluster — a second caller gets a clear
        # RescaleInProgressError, never an interleaved redeploy
        self._topology_busy: Optional[str] = None
        # (job, fragment) → "vnode"|"source": a rescale whose ROLLBACK
        # failed leaves state possibly straddling namespaces; the next
        # recovery re-routes it to the recorded placements (repair)
        self._pending_repair: Dict[Tuple[str, int], str] = {}
        # chaos seam: one-shot (phase, fn) fired at that rescale phase
        # — how the harness kills a worker mid-redeploy deterministically
        self.rescale_fault_hook: Optional[tuple] = None
        # dedicated compaction (ISSUE 19): one compactor-role
        # subprocess + a CompactionManager with one namespace per
        # worker slot; 'inline' = workers compact on their own commit
        # path (the oracle arm)
        self._compaction_mode = "inline"
        self._compaction_mgr = None
        self._compactor_handle: Optional[WorkerHandle] = None
        self._compactor_client: Optional[WorkerClient] = None
        self.compactor_respawns = 0
        # exactly-once sinks (ISSUE 20): the meta-side coordinator —
        # workers stage INLINE at barrier passage (deferred=False
        # registrations), this side owns manifest commits at the
        # checkpoint floor and the recovery promote/truncate sweep
        from risingwave_tpu.meta.sink_coordinator import SinkCoordinator
        self.sinks = SinkCoordinator()

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        await asyncio.gather(*(self._start_slot(k)
                               for k in range(self.n)))
        await self._fresh_barrier_plane()

    async def _start_slot(self, k: int) -> None:
        h = WorkerHandle(os.path.join(self.root, f"w{k}"),
                         platform=self.platform)
        self.clients[k] = await h.start()
        self.handles[k] = h

    async def _fresh_barrier_plane(self) -> None:
        """(Re)build the barrier fan-out. Off arm: one global loop,
        one pseudo-actor per worker slot. Plane arm: one BarrierPlane
        whose domains rebuild from the deployed jobs' recorded
        ``domain_keys`` — after a recovery every domain's initial
        barrier recovers ``prev = the committed floor``, re-aligning
        all domains to the same durable point."""
        self.local = LocalBarrierManager()
        # release the previous generation's per-domain control
        # connections (reset-in-place recoveries keep the worker
        # processes alive — without the abort every recovery round
        # would leak domains × workers open sockets)
        for w in self._domain_wiring.values():
            for c in w["clients"]:
                if c is not None:
                    c.abort()
        self._domain_wiring = {}
        if not self.epoch_pipeline:
            # distributed=True: the ledger's sealed records cover only
            # coordinator-side phases until drain_ledger merges the
            # worker accumulators in (conservation defers to the merge)
            self.loop = BarrierLoop(
                self.local, self.store,
                collect_timeout_s=self.barrier_timeout_s,
                distributed=True)
            self._plane = None
            for k in range(self.n):
                pid = _PSEUDO_BASE + k
                self.local.register_sender(
                    pid, WorkerBarrierSender(
                        self.clients[k], self.local, pid,
                        committed_fn=lambda:
                        self.store.committed_epoch()))
            self.local.set_expected_actors(
                [_PSEUDO_BASE + k for k in range(self.n)])
            self.loop.uploader.sinks = self.sinks
            return
        from risingwave_tpu.meta.domains import BarrierPlane
        self._plane = BarrierPlane(
            self.local, self.store,
            collect_timeout_s=self.barrier_timeout_s,
            distributed=True)
        self._plane.aligned_hook = self._seal_sync_workers
        self.loop = self._plane
        # sink manifests commit in the uploader hooks: strictly after
        # the floor is durable (and the aligned_hook has sealed every
        # worker — inline staging is already on disk by collection)
        self.loop.uploader.sinks = self.sinks
        for name, job in self.jobs.items():
            self._plane.assign_job(name, set(job.domain_keys),
                                   sender_ids=(), expected_ids=(),
                                   actor_ids=job.actor_ids())
        await self._rewire_domains()

    def _domain_extras_fn(self, domain: str):
        """Builds the per-barrier domain frame: the actor filter the
        worker scopes the barrier to, and the cross-domain write floor
        it may fence the store to."""
        def extras(_barrier) -> dict:
            actors = sorted(a for a in
                            self._plane.domain_actors(domain)
                            if a < _PSEUDO_BASE)
            # "domain" rides along so the WORKER can run the
            # bottleneck walk over its own chains per barrier (the
            # autoscaler's signal on a distributed session)
            return {"actors": actors, "domain": domain,
                    "seal": self._plane.allocator.write_floor()}
        return extras

    async def _wire_domain(self, domain: str) -> None:
        """Open one control connection per worker slot for a new
        domain and register its barrier senders. Separate connections
        are the point: two domains' inject RPCs on one request-
        response channel would serialize — the slow domain's collect
        would block the fast domain's inject, resurrecting the global
        lockstep at the transport layer."""
        self._domain_seq += 1
        pids, clients = [], []
        for k in range(self.n):
            base = self.clients[k]
            if base is None:
                pids.append(None)
                clients.append(None)
                continue
            c = WorkerClient(base.host, base.control_port,
                             base.exchange_port)
            await c.connect()
            pid = _PSEUDO_BASE + self._domain_seq * 256 + k
            self.local.register_sender(
                pid, WorkerBarrierSender(
                    c, self.local, pid,
                    committed_fn=lambda: self.store.committed_epoch(),
                    extras_fn=self._domain_extras_fn(domain)))
            pids.append(pid)
            clients.append(c)
        self._domain_wiring[domain] = {"pids": pids,
                                       "clients": clients}
        self._plane.set_domain_channel(
            domain, [p for p in pids if p is not None])

    async def _rewire_domains(self) -> None:
        """Reconcile per-domain wiring with the plane's live domains
        (deploys create domains; merges absorb them; drops retire
        them)."""
        live = {d for d in self._plane.domains()
                if self._plane.domain_actors(d)
                or d in {self._plane.domain_of_job(j)
                         for j in self.jobs}}
        for dom in list(self._domain_wiring):
            if dom not in live:
                w = self._domain_wiring.pop(dom)
                for pid in w["pids"]:
                    if pid is not None:
                        self.local.drop_actor(pid)
                for c in w["clients"]:
                    if c is not None:
                        c.abort()
        for dom in live:
            if dom not in self._domain_wiring:
                await self._wire_domain(dom)
            else:
                # a merge may have folded an absorbed domain's pseudo
                # actors into the survivor's member sets — scrub them
                # back to exactly this domain's wired channel, or the
                # next barrier would wait on dead pseudo actors
                self._plane.set_domain_channel(
                    dom, [p for p in self._domain_wiring[dom]["pids"]
                          if p is not None])

    async def _seal_sync_workers(self, floor: int) -> None:
        """Aligned-checkpoint push: every worker seals + stage-syncs
        to the floor BEFORE the coordinator watermark advances — the
        committed epoch recovery trusts is durable on every slot."""
        await asyncio.gather(*(
            c.call_idempotent({"cmd": "seal_sync", "epoch": floor},
                              io_timeout=60.0)
            for c in self.clients if c is not None))

    def _all_pseudo(self) -> Set[int]:
        if self._plane is None:
            return {_PSEUDO_BASE + k for k in range(self.n)}
        return {pid for w in self._domain_wiring.values()
                for pid in w["pids"] if pid is not None}

    def _stop_set(self, *jobs: JobDeployment) -> frozenset:
        """Actor ids to stop (plus every worker pseudo-actor — the
        stop barrier must still collect on every slot)."""
        ids = {a for j in jobs for a in j.actor_ids()}
        return frozenset(ids | self._all_pseudo())

    async def stop(self) -> None:
        if self._compaction_mgr is not None:
            mgr, self._compaction_mgr = self._compaction_mgr, None
            await mgr.drain()
        if self.loop is not None:
            await self.loop.inject_and_collect(
                force_checkpoint=True,
                mutation=StopMutation(
                    self._stop_set(*self.jobs.values())))
        for h in self.handles:
            if h is not None:
                await h.stop()
        await self._stop_compactor()

    def kill_slot(self, k: int) -> None:
        """SIGKILL one worker (chaos path: no goodbye, no flush).
        Deliberately does NOT reap: the corpse stays visible to
        dead_slots() until a recovery handles it, like a real crash."""
        if self.handles[k] is not None and self.handles[k].proc \
                is not None:
            self.handles[k].proc.kill()

    # -- failure detection ------------------------------------------------
    def dead_slots(self) -> List[int]:
        """The supervisor's dead set: slots whose subprocess exited
        (poll) plus slots whose heartbeat lease expired (alive but
        wedged — enable_liveness feeds these)."""
        out = {k for k, h in enumerate(self.handles)
               if h is None or not h.alive()}
        out |= self._expired_slots
        return sorted(out)

    def enable_liveness(self, max_interval_s: float = 5.0) -> None:
        """Heartbeat-expiry detection: register every slot in a
        ClusterManager and ping through a Heartbeater whose ticks the
        serving loop drives explicitly (no background task — ticks are
        deterministic under test drivers). Expired leases land in the
        supervisor's dead set via ``dead_slots()``. Re-invoked after
        every recovery (clients change)."""
        from risingwave_tpu.meta.cluster import ClusterManager

        self._manager = ClusterManager(
            max_heartbeat_interval_s=max_interval_s)
        self._wid_slot = {}
        self._heartbeater = Heartbeater(
            self._manager, on_expired=self._note_expired)
        for k, c in enumerate(self.clients):
            if c is None:
                continue
            w = self._manager.add_worker("127.0.0.1", c.control_port)
            self._wid_slot[w.worker_id] = k
            self._heartbeater.register(w.worker_id, c)

    def _note_expired(self, dead_nodes) -> None:
        for w in dead_nodes:
            slot = self._wid_slot.get(w.worker_id)
            if slot is not None:
                self._expired_slots.add(slot)

    async def liveness_tick(self) -> list:
        """One heartbeat round (serving loops call this per beat)."""
        if self._heartbeater is None:
            return []
        return await self._heartbeater.tick()

    # -- scheduling (schedule.rs analog) ----------------------------------
    def _place(self, graph: FragmentGraph) -> List[List[tuple]]:
        """Round-robin actors over worker slots; each hash fragment's
        actor list order defines its vnode mapping order."""
        placements = []
        for frag in graph.fragments:
            actors = []
            for _ in range(frag.parallelism):
                slot = self._rr % self.n
                self._rr += 1
                actors.append((self._next_actor, slot))
                self._next_actor += 1
            placements.append(actors)
        return placements

    def _expand_nodes(self, frag: Fragment, actor_id: int,
                      placements: List[List[tuple]],
                      splits: Optional[List[int]] = None,
                      rank: int = 0,
                      n_actors: int = 1) -> List[dict]:
        """Resolve exchange_in placeholders into per-upstream-actor
        remote_input nodes + a merge, and pin the source actor id.
        ``splits`` (filelog fragments) is THIS actor's partition
        subset, stamped into the connector options so the worker
        builds a reader over exactly those splits. ``rank`` /
        ``n_actors`` stamp sink nodes with their writer identity —
        each parallel actor is one of the N exactly-once writers."""
        out: List[dict] = []
        remap: Dict[int, int] = {}
        for idx, node in enumerate(frag.nodes):
            if node["op"] == "exchange_in":
                inp = frag.inputs[node["port"]]
                r_idxs = []
                for up_aid, up_slot in placements[inp.up_frag]:
                    out.append({
                        "op": "remote_input", "host": "127.0.0.1",
                        "port": self.clients[up_slot].exchange_port,
                        "up_actor": up_aid, "schema": inp.schema})
                    r_idxs.append(len(out) - 1)
                from risingwave_tpu.stream.coalesce import (
                    DEFAULT_MAX_CHUNKS,
                )
                out.append({"op": "merge", "inputs": r_idxs,
                            # session knobs ride the cut edge: rows=0
                            # disables fan-in re-coalescing end to end
                            "coalesce_rows": int(getattr(
                                inp, "coalesce_rows", 0)),
                            "coalesce_chunks": int(getattr(
                                inp, "coalesce_chunks",
                                DEFAULT_MAX_CHUNKS))})
                remap[idx] = len(out) - 1
                continue
            n2 = remap_node_refs(node, remap)
            if n2["op"] == "source":
                n2["actor_id"] = actor_id
                if splits is not None:
                    conn = dict(n2.get("connector") or {})
                    conn["partitions"] = ",".join(str(p)
                                                  for p in splits)
                    n2["connector"] = conn
            elif n2["op"] == "sink":
                n2["writer"] = int(rank)
                n2["n_writers"] = int(n_actors)
            out.append(n2)
            remap[idx] = len(out) - 1
        return out

    def _wiring(self, fi: int, graph: FragmentGraph,
                placements: List[List[tuple]]) -> tuple:
        """(outputs, dispatch) for fragment fi's actors — hash over the
        consumer's actors with a uniform vnode mapping, simple when the
        consumer is a single actor."""
        consumers = graph.consumers_of(fi)
        if not consumers:
            return [], None
        assert len(consumers) == 1, "tree plans have one consumer"
        down_fi, inp = consumers[0]
        outs = [aid for aid, _slot in placements[down_fi]]
        if inp.mode == "broadcast" and len(outs) > 1:
            return outs, {"type": "broadcast"}
        if len(outs) == 1:
            return outs, {"type": "simple"}
        from risingwave_tpu.common.hash import VnodeMapping
        mapping = VnodeMapping.new_uniform(len(outs))
        return outs, {"type": "hash", "keys": inp.keys,
                      "mapping": [int(o) for o in mapping.owners]}

    async def deploy_graph(self, name: str, graph: FragmentGraph,
                           domain_keys=()) -> JobDeployment:
        """Schedule + deploy one job's fragments (upstream first so
        exchange edges exist before consumers connect), then leave
        activation to the caller's next barrier. A partial failure
        unwinds: already-deployed actors stop at a barrier — left
        running, a source feeding an edge nobody consumes would block
        on the credit window and wedge every later barrier.
        ``domain_keys`` (source/MV names the job reads) anchor its
        barrier domain: jobs sharing one align together."""
        if name in self.jobs:
            raise ValueError(f"job {name!r} already deployed")
        job = JobDeployment(name, graph, self._place(graph),
                            domain_keys=frozenset(domain_keys))
        for fi, frag in enumerate(graph.fragments):
            if self._source_rescalable(frag):
                # Kafka-parity split assignment: ALL of the topic's
                # partitions round-robin over the fragment's actors
                # (one actor owns them all at parallelism 1)
                job.split_assignments[fi] = self._round_robin_splits(
                    self._source_partitions(frag),
                    len(job.placements[fi]))
        try:
            await self._deploy_job(job)
        except BaseException:
            if self.loop is not None:
                await self.loop.inject_and_collect(
                    force_checkpoint=True,
                    mutation=StopMutation(self._stop_set(job)))
            raise
        self.jobs[name] = job
        if self._plane is not None:
            self._plane.assign_job(name, set(job.domain_keys),
                                   sender_ids=(), expected_ids=(),
                                   actor_ids=job.actor_ids())
            await self._rewire_domains()
        return job

    async def _deploy_job(self, job: JobDeployment) -> None:
        # fragments deploy upstream-first (edges must exist before
        # consumers connect); a fragment's actors deploy concurrently
        for fi, frag in enumerate(job.graph.fragments):
            outputs, dispatch = self._wiring(fi, job.graph,
                                             job.placements)
            assign = job.split_assignments.get(fi)
            await asyncio.gather(*(
                self.clients[slot].deploy_plan(
                    self._expand_nodes(
                        frag, aid, job.placements,
                        splits=assign[rank] if assign is not None
                        else None, rank=rank,
                        n_actors=len(job.placements[fi])),
                    actor_id=aid, outputs=outputs, dispatch=dispatch,
                    job=job.name)
                for rank, (aid, slot)
                in enumerate(job.placements[fi])))

    async def drop_job(self, name: str) -> None:
        job = self.jobs.pop(name, None)
        if job is None:
            raise KeyError(name)
        await self.loop.inject_and_collect(
            force_checkpoint=True,
            mutation=StopMutation(self._stop_set(job)))
        if self._plane is not None:
            self._plane.remove_job(name)
            await self._rewire_domains()

    # -- barriers ---------------------------------------------------------
    async def step(self, n: int = 1) -> None:
        for _ in range(n):
            await self.loop.inject_and_collect(force_checkpoint=True)

    # -- epoch-causal tracing ---------------------------------------------
    async def set_trace(self, on: bool) -> None:
        """Fan the tracing toggle out to every worker process (the
        coordinator's own tracer is the caller's to flip). Remembered
        so a respawned worker rejoins with the operator's setting,
        not the module default."""
        self._trace_on = bool(on)
        await asyncio.gather(*(
            c.call({"cmd": "set_trace", "on": bool(on)})
            for c in self.clients if c is not None))

    async def set_ledger(self, on: bool) -> None:
        """Fan the phase-ledger toggle out to every worker process
        (same on/off everywhere, or a drained merge would have
        per-process holes). Remembered for respawns like set_trace."""
        self._ledger_on = bool(on)
        await asyncio.gather(*(
            c.call({"cmd": "set_ledger", "on": bool(on)})
            for c in self.clients if c is not None))

    async def set_costs(self, on: bool) -> None:
        """Fan the cost/skew-attribution toggle out to every worker
        (per-MV cost books, topology upkeep and hot-key sketches flip
        together). Remembered for respawns like set_ledger."""
        self._costs_on = bool(on)
        await asyncio.gather(*(
            c.call({"cmd": "set_costs", "on": bool(on)})
            for c in self.clients if c is not None))

    # -- dedicated compaction (ISSUE 19) ----------------------------------
    async def set_compaction(self, mode: str) -> None:
        """Fan the compaction arm to every worker namespace and
        (de)provision the compactor role. 'dedicated' spawns ONE
        compactor subprocess plus a CompactionManager with one
        namespace per worker slot; 'inline' drains in-flight tasks,
        reverts workers to commit-path compaction and stops the
        compactor. Remembered across respawns/recoveries like
        set_trace."""
        from risingwave_tpu.meta.compaction import parse_compaction
        mode = parse_compaction(mode)
        self._compaction_mode = mode
        await asyncio.gather(*(
            c.call_idempotent({"cmd": "set_compaction", "mode": mode},
                              io_timeout=20.0)
            for c in self.clients if c is not None))
        if mode == "dedicated":
            if self._compactor_handle is None:
                await self._start_compactor()
            if self._compaction_mgr is None:
                from risingwave_tpu.meta.compaction import (
                    CompactionManager,
                )
                self._compaction_mgr = CompactionManager(
                    on_fault=self._on_compactor_fault)
                for k in range(self.n):
                    self._compaction_mgr.add_namespace(
                        f"w{k}", self._compaction_hooks(k))
        else:
            mgr, self._compaction_mgr = self._compaction_mgr, None
            if mgr is not None:
                await mgr.drain()
            await self._stop_compactor()

    async def _start_compactor(self) -> None:
        h = WorkerHandle(os.path.join(self.root, "compactor"),
                         platform=self.platform, role="compactor")
        self._compactor_client = await h.start()
        self._compactor_handle = h

    async def _stop_compactor(self) -> None:
        h, self._compactor_handle = self._compactor_handle, None
        self._compactor_client = None
        if h is None:
            return
        try:
            await h.stop()
        except BaseException:  # noqa: BLE001 — a chaos-killed corpse
            h.kill()           # cannot answer the stop verb; reap it

    def kill_compactor(self) -> None:
        """SIGKILL the compactor role (chaos path). Serving is
        untouched by design: the in-flight task's lease expires, the
        manager aborts + requeues, compaction_tick respawns the
        process."""
        h = self._compactor_handle
        if h is not None and h.proc is not None:
            h.proc.kill()

    def _compaction_hooks(self, k: int):
        """Hooks for slot k's namespace. snapshot/reserve/apply/abort
        run on the OWNING worker over its control channel — resolved
        at call time, because recoveries swap ``clients[k]``; execute
        dispatches the merge to the compactor role pointed at the
        worker's namespace directory."""
        from risingwave_tpu.meta.compaction import CompactorHooks

        def client() -> WorkerClient:
            c = self.clients[k]
            if c is None:
                raise ConnectionError(f"worker slot {k} down")
            return c

        async def snapshot():
            r = await client().call_idempotent(
                {"cmd": "level_snapshot"}, io_timeout=20.0)
            return r["snapshot"]

        async def reserve(input_ids, id_block):
            return await client().call(
                {"cmd": "compact_reserve", "inputs": input_ids,
                 "id_block": id_block}, io_timeout=20.0)

        async def apply(input_ids, outputs):
            return await client().call(
                {"cmd": "compact_apply", "inputs": input_ids,
                 "outputs": outputs}, io_timeout=20.0)

        async def abort(input_ids, output_ids):
            return await client().call_idempotent(
                {"cmd": "compact_abort", "inputs": input_ids,
                 "outputs": output_ids}, io_timeout=20.0)

        async def execute(task):
            c = self._compactor_client
            if c is None:
                raise ConnectionError("compactor down")
            return await c.call(
                {"cmd": "compact_task",
                 "store": os.path.join(self.root, f"w{k}"),
                 "task": task}, io_timeout=60.0)

        return CompactorHooks(snapshot=snapshot, reserve=reserve,
                              apply=apply, abort=abort,
                              execute=execute)

    def _on_compactor_fault(self, ns: str, kind: str, exc) -> None:
        """A compactor fault costs a TASK, never a serving domain:
        record the requeue in rw_recovery directly — NEVER through
        supervisor.admit(), whose storm budget belongs to serving
        recoveries."""
        detail = f"{ns}: {kind}"
        if exc is not None:
            detail = f"{detail}: {exc!r}"
        self.supervisor.record(
            CAUSE_COMPACTOR_DEAD, ACTION_REQUEUE, (),
            self.store.committed_epoch(), 0.0, True, 1,
            detail=detail[:200])

    async def compaction_tick(self) -> Optional[dict]:
        """One manager round (the distributed session calls this after
        each barrier). Heals a dead compactor process FIRST: task
        recovery must not wait on a corpse that can never finish."""
        mgr = self._compaction_mgr
        if mgr is None:
            return None
        h = self._compactor_handle
        if h is not None and not h.alive():
            h.kill()                     # reap (idempotent)
            await self._start_compactor()
            self.compactor_respawns += 1
        return await mgr.tick()

    async def drain_trace(self) -> int:
        """Pull every worker's recorded spans into the coordinator's
        flight recorder, tagged by worker slot — a drained span leaves
        the worker, so repeated drains never duplicate."""
        from risingwave_tpu.utils.spans import EPOCH_TRACER
        # keep the REAL slot index next to each reply: enumerating the
        # None-filtered list would shift every tag left of a dead slot
        # and attribute a live worker's spans to the wrong process
        live = [(k, c) for k, c in enumerate(self.clients)
                if c is not None]
        replies = await asyncio.gather(*(
            c.call({"cmd": "drain_trace"}) for _k, c in live))
        n = 0
        for (k, _c), reply in zip(live, replies):
            n += EPOCH_TRACER.ingest(reply.get("spans", ()),
                                     worker=f"worker-{k}")
        # the watchdog promoted slow barriers BEFORE these spans
        # arrived: recompute their straggler lines over the full view
        EPOCH_TRACER.refresh_diagnoses()
        return n

    async def drain_ledger(self) -> int:
        """Pull every worker's open phase-ledger accumulators into the
        coordinator's ledger (merged into the sealed records of the
        same epochs — this is what makes a distributed epoch's
        conservation residual meaningful). Drained accumulators leave
        the worker, so repeated drains never double-count."""
        from risingwave_tpu.utils.ledger import LEDGER
        live = [(k, c) for k, c in enumerate(self.clients)
                if c is not None]
        replies = await asyncio.gather(*(
            c.call({"cmd": "drain_ledger"}) for _k, c in live))
        # conservation resolves only when EVERY worker's books arrived
        # — with a dead slot the record's residual would be a phantom
        # of the missing process, so the exemption stands
        complete = len(live) == self.n
        n = 0
        for (k, _c), reply in zip(live, replies):
            n += LEDGER.ingest(reply.get("epochs", ()),
                               worker=f"worker-{k}",
                               resolve=complete)
        return n

    async def drain_freshness(self) -> int:
        """Pull every worker's raw freshness parts (ingest hwms, epoch
        frontiers, visibility events) into the coordinator's tracker —
        a source fragment on worker 0 and its materialize on worker 1
        resolve into one per-MV lag series here. Returns visibility
        events resolved."""
        from risingwave_tpu.stream.freshness import FRESHNESS
        live = [c for c in self.clients if c is not None]
        replies = await asyncio.gather(*(
            c.call({"cmd": "drain_freshness"}) for c in live))
        n = 0
        for reply in replies:
            n += FRESHNESS.ingest(reply.get("parts") or {})
        return n

    async def drain_signals(self, light: bool = False) -> int:
        """Pull every worker's autoscaler signal snapshot — the
        utilization tricolor rows and the worker-side bottleneck-walker
        state — into the coordinator's process-global views. Actor ids
        are cluster-unique, so worker rows merge collision-free; the
        walker merge keeps the strongest per-domain candidate across
        processes. Feeds rw_actor_utilization / rw_bottlenecks on the
        distributed session and the autoscaler's tick."""
        from risingwave_tpu.state.topology import TOPOLOGY
        from risingwave_tpu.stream.bottleneck import BOTTLENECKS
        from risingwave_tpu.stream.costs import COSTS
        from risingwave_tpu.stream.hotkeys import HOTKEYS
        from risingwave_tpu.stream.monitor import UTILIZATION
        live = [(k, c) for k, c in enumerate(self.clients)
                if c is not None]
        replies = await asyncio.gather(*(
            c.call_idempotent({"cmd": "signals", "light": light},
                              io_timeout=20.0)
            for _k, c in live))
        n = 0
        for (k, _c), reply in zip(live, replies):
            n += UTILIZATION.ingest_rows(reply.get("utilization")
                                         or ())
            n += BOTTLENECKS.ingest(reply.get("bottlenecks") or (),
                                    worker=f"worker-{k}")
            # attribution surfaces (ISSUE 16): topology/hot-key
            # snapshots replace per worker (absent on a light drain —
            # replacing with () would wipe the cached snapshot); cost
            # books fold as true-drain deltas every time
            if "topology" in reply:
                n += TOPOLOGY.ingest(reply["topology"] or (),
                                     worker=f"worker-{k}")
            if "hot_keys" in reply:
                n += HOTKEYS.ingest(reply["hot_keys"] or (),
                                    worker=f"worker-{k}")
            n += COSTS.ingest(reply.get("mv_costs") or {},
                              worker=f"worker-{k}")
        # evict rows for actors no rescale/recovery kept: ingested
        # copies have no worker-side drop to mirror, and every
        # redeploy mints fresh actor ids
        UTILIZATION.prune(a for j in self.jobs.values()
                          for a in j.actor_ids())
        return n

    def domain_of_job(self, name: str) -> str:
        """The barrier domain a deployed job's epochs flow through
        ("" = the global loop / off arm)."""
        if self._plane is None:
            return ""
        return self._plane.domain_of_job(name) or ""

    # -- distributed reads ------------------------------------------------
    async def scan_table(self, table_id: int) -> List[tuple]:
        """Union a table's committed rows across every namespace
        (vnode-disjoint, so plain concatenation then key-sort). The
        scan pins the COORDINATOR's committed epoch: workers lag one
        barrier behind (the commit decision pipelines), but their
        staged SSTs are readable at any epoch — this keeps FLUSH →
        SELECT read-your-writes like the in-process session."""
        epoch = self.store.committed_epoch()
        parts = await asyncio.gather(
            *(c.scan_table(table_id, epoch=epoch)
              for c in self.clients if c is not None))
        rows: List[tuple] = [kv for part in parts for kv in part]
        rows.sort(key=lambda kv: kv[0])
        return rows

    # -- recovery (recovery.rs:110 collapsed) -----------------------------
    async def recover(self) -> None:
        """Full-cluster recovery to the coordinator's committed epoch:
        kill every slot, restart over the same namespaces, discard
        uncommitted staged state, redeploy all jobs. The next barrier
        resumes sources from their recovered offsets."""
        floor = self.store.committed_epoch()
        for k in range(self.n):
            if self.handles[k] is not None:
                self.handles[k].kill()
        await asyncio.gather(*(self._start_slot(k)
                               for k in range(self.n)))
        await asyncio.gather(*(
            self.clients[k].call({"cmd": "recover_store",
                                  "epoch": floor})
            for k in range(self.n)))
        # sink sweep BEFORE any writer redeploys: epochs the floor
        # covers promote (their staging was durable before the floor
        # advanced), younger staging truncates — replayed rows
        # re-stage under fresh epochs, never duplicating
        self.sinks.recover(floor)
        if self._compaction_mode != "inline":
            await asyncio.gather(*(
                self.clients[k].call_idempotent(
                    {"cmd": "set_compaction",
                     "mode": self._compaction_mode}, io_timeout=20.0)
                for k in range(self.n)))
        await self._fresh_barrier_plane()
        await self._run_pending_repairs()
        for job in self.jobs.values():
            await self._deploy_job(job)
        if self._heartbeater is not None:
            self.enable_liveness(self._manager.max_interval)

    async def _respawn_slot(self, k: int) -> None:
        """Restart one DEAD slot's subprocess over its namespace."""
        if self.handles[k] is not None:
            self.handles[k].kill()       # reap the corpse (idempotent)
        await self._start_slot(k)
        # a fresh process boots with the MODULE defaults — re-apply
        # the operator's trace/ledger toggles or the respawned worker
        # punches a per-process hole in every later drain/merge
        for verb, on in (("set_trace", getattr(self, "_trace_on",
                                               None)),
                         ("set_ledger", getattr(self, "_ledger_on",
                                                None)),
                         ("set_costs", getattr(self, "_costs_on",
                                               None))):
            if on is not None:
                await self.clients[k].call_idempotent(
                    {"cmd": verb, "on": on}, io_timeout=20.0)
        if self._compaction_mode != "inline":
            # a fresh process boots inline — without this re-apply the
            # respawned worker would compact on its own commit path,
            # racing (and conflicting with) the manager's reservations
            await self.clients[k].call_idempotent(
                {"cmd": "set_compaction",
                 "mode": self._compaction_mode}, io_timeout=20.0)

    async def _reset_slot(self, k: int) -> None:
        """Rejoin one LIVE slot in place: fresh control connection
        (the old one may be desynced or holding a wedged RPC), then
        the worker drops its actors and exchange edges while keeping
        the process — and its warm jit caches — alive."""
        old = self.clients[k]
        c = WorkerClient(old.host, old.control_port,
                         old.exchange_port)
        await c.connect()
        old.abort()
        self.clients[k] = c
        if self.handles[k] is not None:
            self.handles[k].client = c
        # bounded: a worker wedged in a blocking call would otherwise
        # hang the recovery itself — past the bound the reset fails,
        # the event records ok=False, and the next round classifies
        # the still-broken state (ending in the storm gate if it
        # never heals)
        await c.call_idempotent({"cmd": "reset"}, io_timeout=20.0,
                                retries=1)

    async def respawn_recover(self, dead: List[int]) -> None:
        """Rung-2 recovery: restart ONLY the dead slots' processes;
        live slots reset in place. Everyone rejoins through the same
        ``recover_store`` handshake at the coordinator's committed
        floor, the barrier plane rebuilds, and every job redeploys —
        all actors were dropped everywhere, because a fragment's
        exchange peers span slots and actor state cannot survive
        partially. With ``dead == []`` (a desynced control channel)
        this degrades to reset-everything-in-place: zero process
        restarts."""
        floor = self.store.committed_epoch()
        dead_set = set(dead)
        await asyncio.gather(*(
            self._respawn_slot(k) if k in dead_set
            else self._reset_slot(k)
            for k in range(self.n)))
        await asyncio.gather(*(
            self.clients[k].call_idempotent(
                {"cmd": "recover_store", "epoch": floor},
                io_timeout=20.0)
            for k in range(self.n)))
        # same promote/truncate sweep as full recovery — a writer
        # killed mid-stage may have left segments above the floor
        self.sinks.recover(floor)
        await self._fresh_barrier_plane()
        await self._run_pending_repairs()
        for job in self.jobs.values():
            await self._deploy_job(job)
        if self._heartbeater is not None:
            self.enable_liveness(self._manager.max_interval)

    async def supervised_recover(self, exc: BaseException
                                 ) -> RecoveryEvent:
        """One supervised recovery round: detect (dead subprocesses +
        expired leases) → classify → admit through the storm gate →
        graduated response → record (rw_recovery row, recovery_total/
        recovery_duration_seconds, recovery.* span chain). Raises
        RecoveryStormError past the consecutive budget; a recovery
        that itself fails records ok=False and re-raises — the next
        beat classifies the new failure."""
        dead = self.dead_slots()
        self._expired_slots.clear()          # consumed into this round
        cause = self.supervisor.classify(exc, dead_workers=dead)
        action = self.supervisor.action_for(cause)
        attempt = await self.supervisor.admit(cause)
        floor = self.store.committed_epoch()
        workers = tuple(dead) if (action == ACTION_RESPAWN and dead) \
            else tuple(range(self.n))
        root = trace_recovery_root(cause, action, floor, attempt)
        t0_wall, t0 = time.time(), time.monotonic()
        ok = False
        try:
            if action == ACTION_RESPAWN:
                await self.respawn_recover(dead)
            else:
                await self.recover()
            ok = True
        finally:
            dur = time.monotonic() - t0
            trace_recovery_phase(
                action, floor, root, t0_wall, dur,
                workers=",".join(str(w) for w in workers))
            ev = self.supervisor.record(
                cause, action, workers, floor, dur, ok, attempt,
                detail=repr(exc)[:200])
        return ev

    # -- reschedule (scale.rs:717 + rebalance_actor_vnode :174) -----------
    # ops whose state is either vnode-partitioned by the exchange keys
    # or derivable from it — fragments of ONLY these ops can rescale
    # with a vnode-sliced state handoff
    # "sink" is trivially rescalable: the epoch-segment writer is
    # STATELESS (visibility is manifest-existence; staged epochs above
    # the recovery floor truncate) — the handoff moves nothing, and
    # the redeploy re-stamps writer ranks for the new actor count
    _RESCALABLE_OPS = frozenset({"exchange_in", "hash_agg", "project",
                                 "filter", "materialize", "sink"})

    def _rescalable(self, frag: Fragment) -> bool:
        if not frag.inputs or any(i.mode != "hash" for i in frag.inputs):
            return False
        for n in frag.nodes:
            if n["op"] not in self._RESCALABLE_OPS:
                return False
            if n["op"] == "materialize" and not n.get("dist_key"):
                return False
        return True

    @contextlib.contextmanager
    def _topology_change(self, desc: str):
        """Serialize topology changes: a second rescale/move arriving
        while one is in flight gets a clear error, never an
        interleaved redeploy of the same domain. (Callers going
        through the session's barrier lock additionally QUEUE —
        this guard is the explicit backstop for direct API use.)"""
        if self._topology_busy is not None:
            raise RescaleInProgressError(
                f"rescale in progress ({self._topology_busy}) — "
                f"topology changes serialize; retry when it completes")
        self._topology_busy = desc
        try:
            yield
        finally:
            self._topology_busy = None

    def _fire_rescale_hook(self, phase: str) -> None:
        if self.rescale_fault_hook is not None \
                and self.rescale_fault_hook[0] == phase:
            _ph, fn = self.rescale_fault_hook
            self.rescale_fault_hook = None
            fn()

    async def rescale_fragment(self, name: str, frag_idx: int,
                               to_slots: List[int]) -> None:
        """Change one fragment's actor set (count AND placement) at a
        stopped barrier: every state row moves to its vnode's NEW
        owner (the 2-byte key prefix IS the vnode — scale.rs's bitmap
        rebalance, made explicit as a scan/slice/ingest handoff across
        per-slot namespaces). Guarded (ISSUE 15): a failure mid-way
        rolls the domain back to the prior topology and state
        placement instead of leaving it half-deployed — see
        ``_guarded_rescale``."""
        from risingwave_tpu.common.hash import VnodeMapping

        job = self.jobs[name]
        frag = job.graph.fragments[frag_idx]
        old = job.placements[frag_idx]
        if len(to_slots) == len(old) and \
                [s for _a, s in old] == list(to_slots):
            return
        if not self._rescalable(frag):
            raise ValueError(
                "fragment is not vnode-rescalable (needs hash inputs "
                "and only exchange_in/hash_agg/project/filter/"
                "materialize-with-dist_key nodes)")
        mapping = VnodeMapping.new_uniform(len(to_slots))

        def owner_of(_tid: int, k: bytes, _v) -> int:
            return to_slots[mapping.owner_of(
                int.from_bytes(k[:2], "big"))]

        with self._topology_change(
                f"{name}/f{frag_idx} -> slots {list(to_slots)}"):
            await self._guarded_rescale(job, frag_idx, list(to_slots),
                                        owner_of, source_assign=None)

    async def rescale_source_fragment(self, name: str, frag_idx: int,
                                      to_slots: List[int]) -> None:
        """Rescale a SOURCE fragment by split reassignment (the
        filelog splits/offsets contract): the topic's partitions
        round-robin over the new actor set, each split's offset row
        migrates to its new owner's namespace, and the redeployed
        readers resume from those byte offsets exactly — no record
        lost, none re-read. Guarded like the vnode path."""
        job = self.jobs[name]
        frag = job.graph.fragments[frag_idx]
        if not self._source_rescalable(frag):
            raise ValueError(
                "fragment is not split-rescalable (needs a filelog "
                "source with a topic and only source/project/filter/"
                "coalesce/row_id_gen nodes)")
        old = job.placements[frag_idx]
        if len(to_slots) == len(old) and \
                [s for _a, s in old] == list(to_slots):
            return
        parts = self._source_partitions(frag)
        assign = self._round_robin_splits(parts, len(to_slots))
        owner_of = self._split_owner_fn(assign, list(to_slots))
        with self._topology_change(
                f"{name}/f{frag_idx} splits -> slots {list(to_slots)}"):
            await self._guarded_rescale(job, frag_idx, list(to_slots),
                                        owner_of,
                                        source_assign=assign)

    @staticmethod
    def _round_robin_splits(parts: List[int],
                            n_actors: int) -> List[List[int]]:
        return [[p for j, p in enumerate(parts)
                 if j % n_actors == rank] for rank in range(n_actors)]

    @staticmethod
    def _split_owner_fn(assign: List[List[int]],
                        to_slots: List[int]) -> Callable:
        part_rank = {p: r for r, ps in enumerate(assign) for p in ps}

        def owner_of(_tid: int, _k: bytes, v) -> int:
            # split rows are (split_id, offset); the partition number
            # is the split id's suffix ("filelog-<topic>-<N>")
            try:
                part = int(str(v[0]).rsplit("-", 1)[1])
            except (ValueError, IndexError, TypeError):
                part = 0
            return to_slots[part_rank.get(part, 0)]
        return owner_of

    async def _guarded_rescale(self, job: JobDeployment, fi: int,
                               to_slots: List[int],
                               owner_of: Callable,
                               source_assign) -> None:
        """The guarded-rescale protocol shared by the vnode and
        split paths: stop the world → route state (copy-at-
        destination FIRST, tombstone second, so no crash point ever
        destroys the only copy of a row) → redeploy the cohort. ANY
        failure past the stop barrier unwinds from the in-memory moved
        log — rows restored at their source, destination copies
        tombstoned, prior topology redeployed — and records the
        rollback in rw_recovery. A rollback that itself fails leaves a
        repair marker the next recovery consumes (re-routing the
        fragment's state to the recorded placements)."""
        frag = job.graph.fragments[fi]
        old_slots = [s for _a, s in job.placements[fi]]
        old_assign = job.split_assignments.get(fi)
        # the rescale cohort is EVERY deployed job, not just the
        # rescaled job's barrier domain: the handoff's worker-side
        # seal fences the whole per-worker store, and a live job in
        # ANY domain would have its next buffered flush rejected under
        # that fence (write at epoch ≤ sealed). Stop-the-world is the
        # scale.rs-parity mechanism; the stall is bounded and recorded
        # (the autoscaler ledger's duration / bench rescale_stall).
        cohort = list(self.jobs.values())
        moved_log: List[tuple] = []
        phase = "stop"
        try:
            await self._stop_and_align_all()
            phase = "handoff"
            self._fire_rescale_hook("handoff")
            fail_point("rescale.handoff")
            handoff_max = await self._route_fragment_state(
                frag, owner_of, sorted(set(old_slots) | set(to_slots)),
                moved_log)
            if handoff_max:
                self.loop.advance_epoch_to(handoff_max)
            phase = "redeploy"
            if source_assign is not None:
                job.split_assignments[fi] = source_assign
            frag.parallelism = len(to_slots)
            self._fire_rescale_hook("redeploy")
            fail_point("rescale.redeploy")
            await self._redeploy_with_fresh_actors(job, {fi: to_slots})
            for j in cohort:
                if j is not job:
                    # stopped-with-the-world siblings come back too
                    await self._redeploy_with_fresh_actors(j, {})
        except BaseException as exc:  # noqa: BLE001 — unwind + rethrow
            await self._rollback_rescale(
                job, fi, old_slots, old_assign,
                source_assign is not None, cohort, moved_log,
                phase, exc)

    async def _route_fragment_state(self, frag: Fragment,
                                    owner_of: Callable,
                                    scan_slots: List[int],
                                    moved_log: List[tuple],
                                    min_epoch: Optional[int] = None
                                    ) -> int:
        """Move every state row of ``frag``'s tables to its owner slot
        (``owner_of(tid, key, row)``). Destination copies ingest
        BEFORE source tombstones: at any interruption point every row
        still exists in at least one namespace, which is what makes
        both the rollback and the post-recovery repair pass sound.
        Appends (tid, src, dst, key, row) per moved row to
        ``moved_log``; returns the highest handoff epoch."""
        if min_epoch is None:
            min_epoch = self.loop.frontier_epoch()
        handoff_max = 0
        for tid in _fragment_table_ids(frag):
            slices: Dict[int, list] = {}
            removals: Dict[int, list] = {}
            for slot in scan_slots:
                if self.clients[slot] is None:
                    continue
                for k, v in await self.clients[slot].scan_table(tid):
                    dst = owner_of(tid, k, v)
                    if dst != slot:
                        slices.setdefault(dst, []).append((k, v))
                        removals.setdefault(slot, []).append(k)
                        moved_log.append((tid, slot, dst, k, v))
            for dst, rows in slices.items():
                r = await self.clients[dst].ingest_table(
                    tid, rows, min_epoch=max(handoff_max, min_epoch))
                handoff_max = max(handoff_max, int(r["epoch"]))
            for slot, keys in removals.items():
                r = await self.clients[slot].ingest_table(
                    tid, [(k, None) for k in keys],
                    min_epoch=max(handoff_max, min_epoch))
                handoff_max = max(handoff_max, int(r["epoch"]))
        return handoff_max

    async def _reverse_handoff(self, moved_log: List[tuple]) -> int:
        """Undo a (possibly partial) handoff from its in-memory moved
        log: restore each moved row at its source slot FIRST, then
        tombstone the destination copy — idempotent at any
        interruption point of the forward pass."""
        min_epoch = self.loop.frontier_epoch()
        handoff_max = 0
        by_src: Dict[tuple, list] = {}
        by_dst: Dict[tuple, list] = {}
        for tid, src, dst, k, v in moved_log:
            by_src.setdefault((src, tid), []).append((k, v))
            by_dst.setdefault((dst, tid), []).append((k, None))
        for (slot, tid), rows in by_src.items():
            r = await self.clients[slot].ingest_table(
                tid, rows, min_epoch=max(handoff_max, min_epoch))
            handoff_max = max(handoff_max, int(r["epoch"]))
        for (slot, tid), rows in by_dst.items():
            r = await self.clients[slot].ingest_table(
                tid, rows, min_epoch=max(handoff_max, min_epoch))
            handoff_max = max(handoff_max, int(r["epoch"]))
        return handoff_max

    async def _rollback_rescale(self, job: JobDeployment, fi: int,
                                old_slots: List[int], old_assign,
                                is_source: bool, cohort,
                                moved_log: List[tuple], phase: str,
                                exc: BaseException) -> None:
        """Unwind a failed rescale to the prior topology, record the
        event in rw_recovery, and raise RescaleError. Failures at the
        ``stop`` phase changed nothing (but the domain's health is
        unknown — a wedged stop barrier needs the supervisor), so only
        the later phases unwind state."""
        name = job.name
        floor = self.store.committed_epoch()
        t0 = time.monotonic()
        rolled = False
        detail = f"phase={phase}: {exc!r}"[:160]
        if phase in ("handoff", "redeploy"):
            # bookkeeping FIRST: whatever recovery runs next must
            # route state and deploy against the PRIOR topology
            if is_source:
                if old_assign is not None:
                    job.split_assignments[fi] = old_assign
                else:
                    job.split_assignments.pop(fi, None)
            job.graph.fragments[fi].parallelism = len(old_slots)
            try:
                handoff_max = await self._reverse_handoff(moved_log)
                if handoff_max:
                    self.loop.advance_epoch_to(handoff_max)
                await self._redeploy_with_fresh_actors(
                    job, {fi: old_slots})
                for j in cohort:
                    if j is not job:
                        await self._redeploy_with_fresh_actors(j, {})
                rolled = True
            except BaseException as rexc:  # noqa: BLE001
                detail += f"; rollback failed: {rexc!r}"[:100]
                # repair marker: state may straddle namespaces — the
                # next recovery re-routes it to the recorded prior
                # placements before redeploying
                self._pending_repair[(name, fi)] = \
                    "source" if is_source else "vnode"
                job.placements[fi] = [(self._fresh_actor(), s)
                                      for s in old_slots]
        self.supervisor.record(
            CAUSE_RESCALE_FAILED, ACTION_ROLLBACK,
            tuple(sorted(set(old_slots))), floor,
            time.monotonic() - t0, rolled, 1,
            detail=f"{name}/f{fi} {detail}")
        if rolled:
            tail = " (rolled back to the prior parallelism)"
        elif phase == "stop":
            tail = " (before any change; domain health unknown)"
        else:
            tail = " (rollback FAILED — the next recovery repairs " \
                   "state placement)"
        raise RescaleError(
            f"rescale of {name!r} fragment {fi} failed during "
            f"{phase}{tail}: {exc!r}", phase, rolled) from exc

    async def _run_pending_repairs(self) -> None:
        """Post-recovery repair pass for rescales whose rollback
        failed: re-route each marked fragment's state to the CURRENT
        recorded placements (dst-first, so the pass is idempotent and
        crash-safe itself), then clear the marker."""
        from risingwave_tpu.common.hash import VnodeMapping
        for (name, fi), kind in list(self._pending_repair.items()):
            job = self.jobs.get(name)
            if job is None or fi >= len(job.placements):
                self._pending_repair.pop((name, fi), None)
                continue
            frag = job.graph.fragments[fi]
            slots = [s for _a, s in job.placements[fi]]
            if kind == "source":
                assign = job.split_assignments.get(
                    fi, self._round_robin_splits(
                        self._source_partitions(frag), len(slots)))
                owner_of = self._split_owner_fn(assign, slots)
            else:
                mapping = VnodeMapping.new_uniform(len(slots))

                def owner_of(_tid, k, _v, _m=mapping, _s=slots):
                    return _s[_m.owner_of(
                        int.from_bytes(k[:2], "big"))]
            handoff_max = await self._route_fragment_state(
                frag, owner_of, list(range(self.n)), [],
                min_epoch=self.store.committed_epoch())
            if handoff_max:
                self.loop.advance_epoch_to(handoff_max)
            self._pending_repair.pop((name, fi), None)

    # source fragments rescalable by split reassignment: root
    # fragments whose only durable state is the source's split/offset
    # table (the filelog contract) — everything else in the chain is
    # stateless
    _SOURCE_RESCALABLE_OPS = frozenset({"source", "project", "filter",
                                        "coalesce", "row_id_gen",
                                        "sink"})

    def _source_rescalable(self, frag: Fragment) -> bool:
        if frag.inputs:
            return False
        src = None
        for n in frag.nodes:
            if n["op"] not in self._SOURCE_RESCALABLE_OPS:
                return False
            if n["op"] == "source":
                src = n
        if src is None or src.get("split_table_id") is None:
            return False
        conn = src.get("connector") or {}
        if str(conn.get("connector", "")).lower() != "filelog":
            return False
        if str(conn.get("segmented", "")).lower() in ("true", "1"):
            return False
        return bool(conn.get("topic"))

    def _source_partitions(self, frag: Fragment) -> List[int]:
        """The topic's current partition set (enumerated from the log
        directory — the coordinator shares the filesystem with the
        workers). Falls back to the single configured partition when
        the directory lists none yet."""
        from risingwave_tpu.connectors.filelog import FileLogEnumerator
        src = next(n for n in frag.nodes if n["op"] == "source")
        conn = src["connector"]
        splits = FileLogEnumerator(conn["path"],
                                   conn["topic"]).list_splits()
        parts = sorted(int(s.split_id.rsplit("-", 1)[1])
                       for s in splits)
        return parts or [int(conn.get("partition", 0))]

    async def move_fragment(self, name: str, frag_idx: int,
                            to_slots: List[int]) -> None:
        """Move one fragment's actors to new worker slots at a stopped
        barrier, shipping its state tables between namespaces (the
        reference's shared storage makes this step implicit; per-slot
        namespaces make it an explicit scan+ingest handoff)."""
        job = self.jobs[name]
        frag = job.graph.fragments[frag_idx]
        if len(to_slots) != len(job.placements[frag_idx]):
            raise ValueError("move keeps the actor count; use "
                             "rescale_fragment for true rescale")
        old = job.placements[frag_idx]
        if len(old) != 1:
            # a whole-namespace scan mixes sibling actors' slices; the
            # vnode-sliced path handles multi-actor fragments
            return await self.rescale_fragment(name, frag_idx,
                                               to_slots)
        if [s for _a, s in old] == list(to_slots):
            return
        # whole-table move through the same guarded protocol the
        # rescales use (dst-first handoff + rollback on failure):
        # every row of the fragment's tables is owned by the one
        # destination slot
        dst = int(to_slots[0])

        def owner_of(_tid: int, _k: bytes, _v) -> int:
            return dst

        with self._topology_change(
                f"move {name}/f{frag_idx} -> slot {dst}"):
            await self._guarded_rescale(job, frag_idx, list(to_slots),
                                        owner_of, source_assign=None)

    async def _stop_and_align_all(self) -> None:
        """Stop EVERY deployed job at one aligned barrier and push the
        commit decision to every worker — the guarded rescale's stop
        phase. Cluster-wide (not just the rescaled job's domain): the
        handoff's worker-side seal fences the whole per-worker store,
        and a still-running job in ANY domain would have its next
        buffered flush rejected under that fence. Stopped jobs have
        nothing pending, so the fence is safe; everyone redeploys with
        the rescaled cohort."""
        await self.loop.inject_and_collect(
            force_checkpoint=True,
            mutation=StopMutation(
                self._stop_set(*self.jobs.values())))
        floor = self.store.committed_epoch()
        for c in self.clients:
            await c.call({"cmd": "recover_store", "epoch": floor})

    async def _redeploy_with_fresh_actors(
            self, job: JobDeployment,
            replaced: Dict[int, List[int]]) -> None:
        """Redeploy every fragment with fresh actor ids (the stopped
        ones are gone from the workers); `replaced` overrides slot
        lists per fragment index."""
        for fi in range(len(job.graph.fragments)):
            slots = replaced.get(
                fi, [s for _a, s in job.placements[fi]])
            job.placements[fi] = [(self._fresh_actor(), s)
                                  for s in slots]
        await self._deploy_job(job)
        if self._plane is not None:
            # the domain's actor filter must name the FRESH actor ids
            # or the redeployed fragments never see another barrier
            self._plane.remove_job(job.name)
            dom = self._plane.assign_job(job.name,
                                         set(job.domain_keys),
                                         sender_ids=(),
                                         expected_ids=(),
                                         actor_ids=job.actor_ids())
            # the handoff ingests committed worker-side ABOVE the
            # coordinator floor — the fresh domain's first barrier
            # must read at/above them, not at the stale floor
            self._plane.advance_domain_to(
                dom, self._plane.last_allocated)
            await self._rewire_domains()

    def _fresh_actor(self) -> int:
        aid = self._next_actor
        self._next_actor += 1
        return aid


def _fragment_table_ids(frag: Fragment) -> List[int]:
    """Every state-table id a fragment's nodes own (the state that must
    move with it)."""
    out: List[int] = []
    for n in frag.nodes:
        op = n["op"]
        if op == "source" and n.get("split_table_id") is not None:
            out.append(int(n["split_table_id"]))
        elif op == "hash_agg":
            out.append(int(n["table_id"]))
            out += [int(v) for v in
                    (n.get("dedup_table_ids") or {}).values()]
            out += [int(v) for v in
                    (n.get("minput_table_ids") or {}).values()]
        elif op == "hash_join":
            out += [int(n["left_table_id"]), int(n["right_table_id"])]
        elif op == "materialize":
            out.append(int(n["table_id"]))
        elif op in ("top_n", "over_window", "eowc_gate", "dedup",
                    "dynamic_filter"):
            out.append(int(n["table_id"]))
        elif op == "backfill":
            out.append(int(n["progress_table_id"]))
        elif op == "watermark_filter" and n.get("table_id") is not None:
            out.append(int(n["table_id"]))
    return out
