"""Cluster: N workers + fragment scheduling + coordinated barriers.

Reference parity: GlobalStreamManager + the actor-graph scheduler
(src/meta/src/stream/stream_manager.rs:161,
src/meta/src/stream/stream_graph/schedule.rs:195-251 — fragments are
scheduled onto parallel units across compute nodes, hash fragments get
the 256-vnode bitmap split among their actors) and GlobalBarrierManager
fan-out (barrier/mod.rs:558 — one InjectBarrier per compute node,
collect-all, then HummockManager::commit_epoch). TPU re-design: each
worker slot owns a hummock namespace under one root; the coordinator
owns the BarrierLoop, pipelines its commit decision onto the next
barrier (two-phase worker stores), and recovery = restart every slot
over its namespace, replay the deployed jobs, resume from the
coordinator's committed epoch (barrier/recovery.rs:110 collapsed).
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from risingwave_tpu.cluster.coordinator import (
    Heartbeater, WorkerBarrierSender, WorkerClient, WorkerHandle,
)
from risingwave_tpu.frontend.fragmenter import Fragment, FragmentGraph
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.meta.supervisor import (
    ACTION_RESPAWN, RecoveryEvent, RecoverySupervisor,
    trace_recovery_phase, trace_recovery_root,
)
from risingwave_tpu.stream.actor import LocalBarrierManager
from risingwave_tpu.stream.message import StopMutation
from risingwave_tpu.stream.plan_ir import remap_node_refs

_PSEUDO_BASE = 1 << 20          # pseudo-actor ids for worker handles


class _CoordEpochStore:
    """BarrierLoop's store shim: epochs COMMIT on the workers (staged
    SSTs adopted via commit_through); the coordinator only tracks the
    committed watermark — the HummockManager version counter without
    the SST bookkeeping."""

    def __init__(self, floor: int = 0):
        self._committed = floor

    def committed_epoch(self) -> int:
        return self._committed

    def seal_epoch(self, epoch: int, is_checkpoint: bool = True) -> None:
        pass

    def sync(self, epoch: int) -> None:
        self._committed = max(self._committed, epoch)


@dataclass
class JobDeployment:
    """One deployed streaming job: its fragment graph + placements.
    placements[fi] = [(actor_id, worker_slot), ...] per fragment.
    ``domain_keys`` are the job's barrier-domain reachability anchors
    (its source/MV dependency names — jobs sharing one align in a
    single domain; recorded so recovery rebuilds the same domains)."""

    name: str
    graph: FragmentGraph
    placements: List[List[tuple]] = field(default_factory=list)
    domain_keys: frozenset = frozenset()

    def actor_ids(self) -> List[int]:
        return [aid for frag in self.placements for aid, _slot in frag]


class Cluster:
    """Coordinator-side handle on N worker processes."""

    def __init__(self, root: str, n_workers: int = 2,
                 platform: str = "cpu",
                 barrier_timeout_s: Optional[float] = None,
                 supervisor: Optional[RecoverySupervisor] = None,
                 epoch_pipeline: bool = True):
        self.root = root
        self.n = n_workers
        self.platform = platform
        self.handles: List[Optional[WorkerHandle]] = [None] * n_workers
        self.clients: List[Optional[WorkerClient]] = [None] * n_workers
        self.jobs: Dict[str, JobDeployment] = {}
        self.local: Optional[LocalBarrierManager] = None
        self.loop = None        # BarrierLoop (off arm) or BarrierPlane
        self.store = _CoordEpochStore()
        # pipelined epochs (ISSUE 13): per-job barrier domains with
        # their own control connections per worker (two domains'
        # injects must not serialize behind one request-response
        # channel); off = the legacy single global loop, bit-identical
        self.epoch_pipeline = bool(epoch_pipeline)
        self._plane = None
        # domain → {"pids": [per-slot pseudo ids], "clients": [...]}
        self._domain_wiring: Dict[str, dict] = {}
        self._domain_seq = 0
        self._next_actor = 1000
        self._rr = 0                      # placement cursor
        # supervised recovery (meta/supervisor.py): classification +
        # storm gate; barrier_timeout_s arms wedged-barrier detection
        self.supervisor = supervisor or RecoverySupervisor()
        self.barrier_timeout_s = barrier_timeout_s
        # heartbeat-expiry detection (enable_liveness): lease-expired
        # slots feed the supervisor's dead set even while their
        # subprocess is technically alive (wedged, not exited)
        self._manager = None
        self._heartbeater: Optional[Heartbeater] = None
        self._expired_slots: Set[int] = set()
        self._wid_slot: Dict[int, int] = {}

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> None:
        await asyncio.gather(*(self._start_slot(k)
                               for k in range(self.n)))
        await self._fresh_barrier_plane()

    async def _start_slot(self, k: int) -> None:
        h = WorkerHandle(os.path.join(self.root, f"w{k}"),
                         platform=self.platform)
        self.clients[k] = await h.start()
        self.handles[k] = h

    async def _fresh_barrier_plane(self) -> None:
        """(Re)build the barrier fan-out. Off arm: one global loop,
        one pseudo-actor per worker slot. Plane arm: one BarrierPlane
        whose domains rebuild from the deployed jobs' recorded
        ``domain_keys`` — after a recovery every domain's initial
        barrier recovers ``prev = the committed floor``, re-aligning
        all domains to the same durable point."""
        self.local = LocalBarrierManager()
        # release the previous generation's per-domain control
        # connections (reset-in-place recoveries keep the worker
        # processes alive — without the abort every recovery round
        # would leak domains × workers open sockets)
        for w in self._domain_wiring.values():
            for c in w["clients"]:
                if c is not None:
                    c.abort()
        self._domain_wiring = {}
        if not self.epoch_pipeline:
            # distributed=True: the ledger's sealed records cover only
            # coordinator-side phases until drain_ledger merges the
            # worker accumulators in (conservation defers to the merge)
            self.loop = BarrierLoop(
                self.local, self.store,
                collect_timeout_s=self.barrier_timeout_s,
                distributed=True)
            self._plane = None
            for k in range(self.n):
                pid = _PSEUDO_BASE + k
                self.local.register_sender(
                    pid, WorkerBarrierSender(
                        self.clients[k], self.local, pid,
                        committed_fn=lambda:
                        self.store.committed_epoch()))
            self.local.set_expected_actors(
                [_PSEUDO_BASE + k for k in range(self.n)])
            return
        from risingwave_tpu.meta.domains import BarrierPlane
        self._plane = BarrierPlane(
            self.local, self.store,
            collect_timeout_s=self.barrier_timeout_s,
            distributed=True)
        self._plane.aligned_hook = self._seal_sync_workers
        self.loop = self._plane
        for name, job in self.jobs.items():
            self._plane.assign_job(name, set(job.domain_keys),
                                   sender_ids=(), expected_ids=(),
                                   actor_ids=job.actor_ids())
        await self._rewire_domains()

    def _domain_extras_fn(self, domain: str):
        """Builds the per-barrier domain frame: the actor filter the
        worker scopes the barrier to, and the cross-domain write floor
        it may fence the store to."""
        def extras(_barrier) -> dict:
            actors = sorted(a for a in
                            self._plane.domain_actors(domain)
                            if a < _PSEUDO_BASE)
            return {"actors": actors,
                    "seal": self._plane.allocator.write_floor()}
        return extras

    async def _wire_domain(self, domain: str) -> None:
        """Open one control connection per worker slot for a new
        domain and register its barrier senders. Separate connections
        are the point: two domains' inject RPCs on one request-
        response channel would serialize — the slow domain's collect
        would block the fast domain's inject, resurrecting the global
        lockstep at the transport layer."""
        self._domain_seq += 1
        pids, clients = [], []
        for k in range(self.n):
            base = self.clients[k]
            if base is None:
                pids.append(None)
                clients.append(None)
                continue
            c = WorkerClient(base.host, base.control_port,
                             base.exchange_port)
            await c.connect()
            pid = _PSEUDO_BASE + self._domain_seq * 256 + k
            self.local.register_sender(
                pid, WorkerBarrierSender(
                    c, self.local, pid,
                    committed_fn=lambda: self.store.committed_epoch(),
                    extras_fn=self._domain_extras_fn(domain)))
            pids.append(pid)
            clients.append(c)
        self._domain_wiring[domain] = {"pids": pids,
                                       "clients": clients}
        self._plane.set_domain_channel(
            domain, [p for p in pids if p is not None])

    async def _rewire_domains(self) -> None:
        """Reconcile per-domain wiring with the plane's live domains
        (deploys create domains; merges absorb them; drops retire
        them)."""
        live = {d for d in self._plane.domains()
                if self._plane.domain_actors(d)
                or d in {self._plane.domain_of_job(j)
                         for j in self.jobs}}
        for dom in list(self._domain_wiring):
            if dom not in live:
                w = self._domain_wiring.pop(dom)
                for pid in w["pids"]:
                    if pid is not None:
                        self.local.drop_actor(pid)
                for c in w["clients"]:
                    if c is not None:
                        c.abort()
        for dom in live:
            if dom not in self._domain_wiring:
                await self._wire_domain(dom)
            else:
                # a merge may have folded an absorbed domain's pseudo
                # actors into the survivor's member sets — scrub them
                # back to exactly this domain's wired channel, or the
                # next barrier would wait on dead pseudo actors
                self._plane.set_domain_channel(
                    dom, [p for p in self._domain_wiring[dom]["pids"]
                          if p is not None])

    async def _seal_sync_workers(self, floor: int) -> None:
        """Aligned-checkpoint push: every worker seals + stage-syncs
        to the floor BEFORE the coordinator watermark advances — the
        committed epoch recovery trusts is durable on every slot."""
        await asyncio.gather(*(
            c.call_idempotent({"cmd": "seal_sync", "epoch": floor},
                              io_timeout=60.0)
            for c in self.clients if c is not None))

    def _all_pseudo(self) -> Set[int]:
        if self._plane is None:
            return {_PSEUDO_BASE + k for k in range(self.n)}
        return {pid for w in self._domain_wiring.values()
                for pid in w["pids"] if pid is not None}

    def _stop_set(self, *jobs: JobDeployment) -> frozenset:
        """Actor ids to stop (plus every worker pseudo-actor — the
        stop barrier must still collect on every slot)."""
        ids = {a for j in jobs for a in j.actor_ids()}
        return frozenset(ids | self._all_pseudo())

    async def stop(self) -> None:
        if self.loop is not None:
            await self.loop.inject_and_collect(
                force_checkpoint=True,
                mutation=StopMutation(
                    self._stop_set(*self.jobs.values())))
        for h in self.handles:
            if h is not None:
                await h.stop()

    def kill_slot(self, k: int) -> None:
        """SIGKILL one worker (chaos path: no goodbye, no flush).
        Deliberately does NOT reap: the corpse stays visible to
        dead_slots() until a recovery handles it, like a real crash."""
        if self.handles[k] is not None and self.handles[k].proc \
                is not None:
            self.handles[k].proc.kill()

    # -- failure detection ------------------------------------------------
    def dead_slots(self) -> List[int]:
        """The supervisor's dead set: slots whose subprocess exited
        (poll) plus slots whose heartbeat lease expired (alive but
        wedged — enable_liveness feeds these)."""
        out = {k for k, h in enumerate(self.handles)
               if h is None or not h.alive()}
        out |= self._expired_slots
        return sorted(out)

    def enable_liveness(self, max_interval_s: float = 5.0) -> None:
        """Heartbeat-expiry detection: register every slot in a
        ClusterManager and ping through a Heartbeater whose ticks the
        serving loop drives explicitly (no background task — ticks are
        deterministic under test drivers). Expired leases land in the
        supervisor's dead set via ``dead_slots()``. Re-invoked after
        every recovery (clients change)."""
        from risingwave_tpu.meta.cluster import ClusterManager

        self._manager = ClusterManager(
            max_heartbeat_interval_s=max_interval_s)
        self._wid_slot = {}
        self._heartbeater = Heartbeater(
            self._manager, on_expired=self._note_expired)
        for k, c in enumerate(self.clients):
            if c is None:
                continue
            w = self._manager.add_worker("127.0.0.1", c.control_port)
            self._wid_slot[w.worker_id] = k
            self._heartbeater.register(w.worker_id, c)

    def _note_expired(self, dead_nodes) -> None:
        for w in dead_nodes:
            slot = self._wid_slot.get(w.worker_id)
            if slot is not None:
                self._expired_slots.add(slot)

    async def liveness_tick(self) -> list:
        """One heartbeat round (serving loops call this per beat)."""
        if self._heartbeater is None:
            return []
        return await self._heartbeater.tick()

    # -- scheduling (schedule.rs analog) ----------------------------------
    def _place(self, graph: FragmentGraph) -> List[List[tuple]]:
        """Round-robin actors over worker slots; each hash fragment's
        actor list order defines its vnode mapping order."""
        placements = []
        for frag in graph.fragments:
            actors = []
            for _ in range(frag.parallelism):
                slot = self._rr % self.n
                self._rr += 1
                actors.append((self._next_actor, slot))
                self._next_actor += 1
            placements.append(actors)
        return placements

    def _expand_nodes(self, frag: Fragment, actor_id: int,
                      placements: List[List[tuple]]) -> List[dict]:
        """Resolve exchange_in placeholders into per-upstream-actor
        remote_input nodes + a merge, and pin the source actor id."""
        out: List[dict] = []
        remap: Dict[int, int] = {}
        for idx, node in enumerate(frag.nodes):
            if node["op"] == "exchange_in":
                inp = frag.inputs[node["port"]]
                r_idxs = []
                for up_aid, up_slot in placements[inp.up_frag]:
                    out.append({
                        "op": "remote_input", "host": "127.0.0.1",
                        "port": self.clients[up_slot].exchange_port,
                        "up_actor": up_aid, "schema": inp.schema})
                    r_idxs.append(len(out) - 1)
                from risingwave_tpu.stream.coalesce import (
                    DEFAULT_MAX_CHUNKS,
                )
                out.append({"op": "merge", "inputs": r_idxs,
                            # session knobs ride the cut edge: rows=0
                            # disables fan-in re-coalescing end to end
                            "coalesce_rows": int(getattr(
                                inp, "coalesce_rows", 0)),
                            "coalesce_chunks": int(getattr(
                                inp, "coalesce_chunks",
                                DEFAULT_MAX_CHUNKS))})
                remap[idx] = len(out) - 1
                continue
            n2 = remap_node_refs(node, remap)
            if n2["op"] == "source":
                n2["actor_id"] = actor_id
            out.append(n2)
            remap[idx] = len(out) - 1
        return out

    def _wiring(self, fi: int, graph: FragmentGraph,
                placements: List[List[tuple]]) -> tuple:
        """(outputs, dispatch) for fragment fi's actors — hash over the
        consumer's actors with a uniform vnode mapping, simple when the
        consumer is a single actor."""
        consumers = graph.consumers_of(fi)
        if not consumers:
            return [], None
        assert len(consumers) == 1, "tree plans have one consumer"
        down_fi, inp = consumers[0]
        outs = [aid for aid, _slot in placements[down_fi]]
        if inp.mode == "broadcast" and len(outs) > 1:
            return outs, {"type": "broadcast"}
        if len(outs) == 1:
            return outs, {"type": "simple"}
        from risingwave_tpu.common.hash import VnodeMapping
        mapping = VnodeMapping.new_uniform(len(outs))
        return outs, {"type": "hash", "keys": inp.keys,
                      "mapping": [int(o) for o in mapping.owners]}

    async def deploy_graph(self, name: str, graph: FragmentGraph,
                           domain_keys=()) -> JobDeployment:
        """Schedule + deploy one job's fragments (upstream first so
        exchange edges exist before consumers connect), then leave
        activation to the caller's next barrier. A partial failure
        unwinds: already-deployed actors stop at a barrier — left
        running, a source feeding an edge nobody consumes would block
        on the credit window and wedge every later barrier.
        ``domain_keys`` (source/MV names the job reads) anchor its
        barrier domain: jobs sharing one align together."""
        if name in self.jobs:
            raise ValueError(f"job {name!r} already deployed")
        job = JobDeployment(name, graph, self._place(graph),
                            domain_keys=frozenset(domain_keys))
        try:
            await self._deploy_job(job)
        except BaseException:
            if self.loop is not None:
                await self.loop.inject_and_collect(
                    force_checkpoint=True,
                    mutation=StopMutation(self._stop_set(job)))
            raise
        self.jobs[name] = job
        if self._plane is not None:
            self._plane.assign_job(name, set(job.domain_keys),
                                   sender_ids=(), expected_ids=(),
                                   actor_ids=job.actor_ids())
            await self._rewire_domains()
        return job

    async def _deploy_job(self, job: JobDeployment) -> None:
        # fragments deploy upstream-first (edges must exist before
        # consumers connect); a fragment's actors deploy concurrently
        for fi, frag in enumerate(job.graph.fragments):
            outputs, dispatch = self._wiring(fi, job.graph,
                                             job.placements)
            await asyncio.gather(*(
                self.clients[slot].deploy_plan(
                    self._expand_nodes(frag, aid, job.placements),
                    actor_id=aid, outputs=outputs, dispatch=dispatch,
                    job=job.name)
                for aid, slot in job.placements[fi]))

    async def drop_job(self, name: str) -> None:
        job = self.jobs.pop(name, None)
        if job is None:
            raise KeyError(name)
        await self.loop.inject_and_collect(
            force_checkpoint=True,
            mutation=StopMutation(self._stop_set(job)))
        if self._plane is not None:
            self._plane.remove_job(name)
            await self._rewire_domains()

    # -- barriers ---------------------------------------------------------
    async def step(self, n: int = 1) -> None:
        for _ in range(n):
            await self.loop.inject_and_collect(force_checkpoint=True)

    # -- epoch-causal tracing ---------------------------------------------
    async def set_trace(self, on: bool) -> None:
        """Fan the tracing toggle out to every worker process (the
        coordinator's own tracer is the caller's to flip). Remembered
        so a respawned worker rejoins with the operator's setting,
        not the module default."""
        self._trace_on = bool(on)
        await asyncio.gather(*(
            c.call({"cmd": "set_trace", "on": bool(on)})
            for c in self.clients if c is not None))

    async def set_ledger(self, on: bool) -> None:
        """Fan the phase-ledger toggle out to every worker process
        (same on/off everywhere, or a drained merge would have
        per-process holes). Remembered for respawns like set_trace."""
        self._ledger_on = bool(on)
        await asyncio.gather(*(
            c.call({"cmd": "set_ledger", "on": bool(on)})
            for c in self.clients if c is not None))

    async def drain_trace(self) -> int:
        """Pull every worker's recorded spans into the coordinator's
        flight recorder, tagged by worker slot — a drained span leaves
        the worker, so repeated drains never duplicate."""
        from risingwave_tpu.utils.spans import EPOCH_TRACER
        # keep the REAL slot index next to each reply: enumerating the
        # None-filtered list would shift every tag left of a dead slot
        # and attribute a live worker's spans to the wrong process
        live = [(k, c) for k, c in enumerate(self.clients)
                if c is not None]
        replies = await asyncio.gather(*(
            c.call({"cmd": "drain_trace"}) for _k, c in live))
        n = 0
        for (k, _c), reply in zip(live, replies):
            n += EPOCH_TRACER.ingest(reply.get("spans", ()),
                                     worker=f"worker-{k}")
        # the watchdog promoted slow barriers BEFORE these spans
        # arrived: recompute their straggler lines over the full view
        EPOCH_TRACER.refresh_diagnoses()
        return n

    async def drain_ledger(self) -> int:
        """Pull every worker's open phase-ledger accumulators into the
        coordinator's ledger (merged into the sealed records of the
        same epochs — this is what makes a distributed epoch's
        conservation residual meaningful). Drained accumulators leave
        the worker, so repeated drains never double-count."""
        from risingwave_tpu.utils.ledger import LEDGER
        live = [(k, c) for k, c in enumerate(self.clients)
                if c is not None]
        replies = await asyncio.gather(*(
            c.call({"cmd": "drain_ledger"}) for _k, c in live))
        # conservation resolves only when EVERY worker's books arrived
        # — with a dead slot the record's residual would be a phantom
        # of the missing process, so the exemption stands
        complete = len(live) == self.n
        n = 0
        for (k, _c), reply in zip(live, replies):
            n += LEDGER.ingest(reply.get("epochs", ()),
                               worker=f"worker-{k}",
                               resolve=complete)
        return n

    async def drain_freshness(self) -> int:
        """Pull every worker's raw freshness parts (ingest hwms, epoch
        frontiers, visibility events) into the coordinator's tracker —
        a source fragment on worker 0 and its materialize on worker 1
        resolve into one per-MV lag series here. Returns visibility
        events resolved."""
        from risingwave_tpu.stream.freshness import FRESHNESS
        live = [c for c in self.clients if c is not None]
        replies = await asyncio.gather(*(
            c.call({"cmd": "drain_freshness"}) for c in live))
        n = 0
        for reply in replies:
            n += FRESHNESS.ingest(reply.get("parts") or {})
        return n

    def domain_of_job(self, name: str) -> str:
        """The barrier domain a deployed job's epochs flow through
        ("" = the global loop / off arm)."""
        if self._plane is None:
            return ""
        return self._plane.domain_of_job(name) or ""

    # -- distributed reads ------------------------------------------------
    async def scan_table(self, table_id: int) -> List[tuple]:
        """Union a table's committed rows across every namespace
        (vnode-disjoint, so plain concatenation then key-sort). The
        scan pins the COORDINATOR's committed epoch: workers lag one
        barrier behind (the commit decision pipelines), but their
        staged SSTs are readable at any epoch — this keeps FLUSH →
        SELECT read-your-writes like the in-process session."""
        epoch = self.store.committed_epoch()
        parts = await asyncio.gather(
            *(c.scan_table(table_id, epoch=epoch)
              for c in self.clients if c is not None))
        rows: List[tuple] = [kv for part in parts for kv in part]
        rows.sort(key=lambda kv: kv[0])
        return rows

    # -- recovery (recovery.rs:110 collapsed) -----------------------------
    async def recover(self) -> None:
        """Full-cluster recovery to the coordinator's committed epoch:
        kill every slot, restart over the same namespaces, discard
        uncommitted staged state, redeploy all jobs. The next barrier
        resumes sources from their recovered offsets."""
        floor = self.store.committed_epoch()
        for k in range(self.n):
            if self.handles[k] is not None:
                self.handles[k].kill()
        await asyncio.gather(*(self._start_slot(k)
                               for k in range(self.n)))
        await asyncio.gather(*(
            self.clients[k].call({"cmd": "recover_store",
                                  "epoch": floor})
            for k in range(self.n)))
        await self._fresh_barrier_plane()
        for job in self.jobs.values():
            await self._deploy_job(job)
        if self._heartbeater is not None:
            self.enable_liveness(self._manager.max_interval)

    async def _respawn_slot(self, k: int) -> None:
        """Restart one DEAD slot's subprocess over its namespace."""
        if self.handles[k] is not None:
            self.handles[k].kill()       # reap the corpse (idempotent)
        await self._start_slot(k)
        # a fresh process boots with the MODULE defaults — re-apply
        # the operator's trace/ledger toggles or the respawned worker
        # punches a per-process hole in every later drain/merge
        for verb, on in (("set_trace", getattr(self, "_trace_on",
                                               None)),
                         ("set_ledger", getattr(self, "_ledger_on",
                                                None))):
            if on is not None:
                await self.clients[k].call_idempotent(
                    {"cmd": verb, "on": on}, io_timeout=20.0)

    async def _reset_slot(self, k: int) -> None:
        """Rejoin one LIVE slot in place: fresh control connection
        (the old one may be desynced or holding a wedged RPC), then
        the worker drops its actors and exchange edges while keeping
        the process — and its warm jit caches — alive."""
        old = self.clients[k]
        c = WorkerClient(old.host, old.control_port,
                         old.exchange_port)
        await c.connect()
        old.abort()
        self.clients[k] = c
        if self.handles[k] is not None:
            self.handles[k].client = c
        # bounded: a worker wedged in a blocking call would otherwise
        # hang the recovery itself — past the bound the reset fails,
        # the event records ok=False, and the next round classifies
        # the still-broken state (ending in the storm gate if it
        # never heals)
        await c.call_idempotent({"cmd": "reset"}, io_timeout=20.0,
                                retries=1)

    async def respawn_recover(self, dead: List[int]) -> None:
        """Rung-2 recovery: restart ONLY the dead slots' processes;
        live slots reset in place. Everyone rejoins through the same
        ``recover_store`` handshake at the coordinator's committed
        floor, the barrier plane rebuilds, and every job redeploys —
        all actors were dropped everywhere, because a fragment's
        exchange peers span slots and actor state cannot survive
        partially. With ``dead == []`` (a desynced control channel)
        this degrades to reset-everything-in-place: zero process
        restarts."""
        floor = self.store.committed_epoch()
        dead_set = set(dead)
        await asyncio.gather(*(
            self._respawn_slot(k) if k in dead_set
            else self._reset_slot(k)
            for k in range(self.n)))
        await asyncio.gather(*(
            self.clients[k].call_idempotent(
                {"cmd": "recover_store", "epoch": floor},
                io_timeout=20.0)
            for k in range(self.n)))
        await self._fresh_barrier_plane()
        for job in self.jobs.values():
            await self._deploy_job(job)
        if self._heartbeater is not None:
            self.enable_liveness(self._manager.max_interval)

    async def supervised_recover(self, exc: BaseException
                                 ) -> RecoveryEvent:
        """One supervised recovery round: detect (dead subprocesses +
        expired leases) → classify → admit through the storm gate →
        graduated response → record (rw_recovery row, recovery_total/
        recovery_duration_seconds, recovery.* span chain). Raises
        RecoveryStormError past the consecutive budget; a recovery
        that itself fails records ok=False and re-raises — the next
        beat classifies the new failure."""
        dead = self.dead_slots()
        self._expired_slots.clear()          # consumed into this round
        cause = self.supervisor.classify(exc, dead_workers=dead)
        action = self.supervisor.action_for(cause)
        attempt = await self.supervisor.admit(cause)
        floor = self.store.committed_epoch()
        workers = tuple(dead) if (action == ACTION_RESPAWN and dead) \
            else tuple(range(self.n))
        root = trace_recovery_root(cause, action, floor, attempt)
        t0_wall, t0 = time.time(), time.monotonic()
        ok = False
        try:
            if action == ACTION_RESPAWN:
                await self.respawn_recover(dead)
            else:
                await self.recover()
            ok = True
        finally:
            dur = time.monotonic() - t0
            trace_recovery_phase(
                action, floor, root, t0_wall, dur,
                workers=",".join(str(w) for w in workers))
            ev = self.supervisor.record(
                cause, action, workers, floor, dur, ok, attempt,
                detail=repr(exc)[:200])
        return ev

    # -- reschedule (scale.rs:717 + rebalance_actor_vnode :174) -----------
    # ops whose state is either vnode-partitioned by the exchange keys
    # or derivable from it — fragments of ONLY these ops can rescale
    # with a vnode-sliced state handoff
    _RESCALABLE_OPS = frozenset({"exchange_in", "hash_agg", "project",
                                 "filter", "materialize"})

    def _rescalable(self, frag: Fragment) -> bool:
        if not frag.inputs or any(i.mode != "hash" for i in frag.inputs):
            return False
        for n in frag.nodes:
            if n["op"] not in self._RESCALABLE_OPS:
                return False
            if n["op"] == "materialize" and not n.get("dist_key"):
                return False
        return True

    async def rescale_fragment(self, name: str, frag_idx: int,
                               to_slots: List[int]) -> None:
        """Change one fragment's actor set (count AND placement) at a
        stopped barrier: every state row moves to its vnode's NEW
        owner (the 2-byte key prefix IS the vnode — scale.rs's bitmap
        rebalance, made explicit as a scan/slice/ingest handoff across
        per-slot namespaces)."""
        from risingwave_tpu.common.hash import VnodeMapping

        job = self.jobs[name]
        frag = job.graph.fragments[frag_idx]
        old = job.placements[frag_idx]
        if len(to_slots) == len(old) and \
                [s for _a, s in old] == list(to_slots):
            return
        if not self._rescalable(frag):
            raise ValueError(
                "fragment is not vnode-rescalable (needs hash inputs "
                "and only exchange_in/hash_agg/project/filter/"
                "materialize-with-dist_key nodes)")
        codomain = self._codomain_jobs(job)
        await self._stop_and_align(job)
        # vnode-sliced handoff: gather each table from every OLD slot,
        # route rows by key-prefix vnode through the NEW mapping, and
        # move ONLY rows whose owner changes (the stationary majority
        # of a small rescale stays put)
        mapping = VnodeMapping.new_uniform(len(to_slots))
        min_epoch = self.loop.frontier_epoch()
        handoff_max = 0
        old_slots = sorted({s for _a, s in old})
        for tid in _fragment_table_ids(frag):
            slices: Dict[int, list] = {}
            for slot in old_slots:
                rows = await self.clients[slot].scan_table(tid)
                moved = []
                for k, v in rows:
                    vnode = int.from_bytes(k[:2], "big")
                    dst = to_slots[mapping.owner_of(vnode)]
                    if dst != slot:
                        slices.setdefault(dst, []).append((k, v))
                        moved.append(k)
                if moved:
                    r = await self.clients[slot].ingest_table(
                        tid, [(k, None) for k in moved],
                        min_epoch=min_epoch)
                    handoff_max = max(handoff_max, int(r["epoch"]))
            for dst, rows in slices.items():
                r = await self.clients[dst].ingest_table(
                    tid, rows, min_epoch=handoff_max or min_epoch)
                handoff_max = max(handoff_max, int(r["epoch"]))
        if handoff_max:
            self.loop.advance_epoch_to(handoff_max)
        await self._redeploy_with_fresh_actors(job, {frag_idx: to_slots})
        for j in codomain:
            if j is not job:
                # stopped-with-the-domain siblings come back too
                await self._redeploy_with_fresh_actors(j, {})

    async def move_fragment(self, name: str, frag_idx: int,
                            to_slots: List[int]) -> None:
        """Move one fragment's actors to new worker slots at a stopped
        barrier, shipping its state tables between namespaces (the
        reference's shared storage makes this step implicit; per-slot
        namespaces make it an explicit scan+ingest handoff)."""
        job = self.jobs[name]
        frag = job.graph.fragments[frag_idx]
        if len(to_slots) != len(job.placements[frag_idx]):
            raise ValueError("move keeps the actor count; use "
                             "rescale_fragment for true rescale")
        old = job.placements[frag_idx]
        if len(old) != 1:
            # a whole-namespace scan mixes sibling actors' slices; the
            # vnode-sliced path handles multi-actor fragments
            return await self.rescale_fragment(name, frag_idx,
                                               to_slots)
        if [s for _a, s in old] == list(to_slots):
            return
        codomain = self._codomain_jobs(job)
        await self._stop_and_align(job)
        # 2) ship the moved actors' state tables between namespaces.
        # Ingest epochs stay ABOVE the last injected barrier (other
        # jobs hold buffered flushes at that epoch; sealing it out from
        # under them would fail their next commit), and the barrier
        # loop then reserves past the handoff epochs.
        min_epoch = self.loop.frontier_epoch()
        handoff_max = 0
        table_ids = _fragment_table_ids(frag)
        for (aid, from_slot), to_slot in zip(old, to_slots):
            if from_slot == to_slot:
                continue
            for tid in table_ids:
                rows = await self.clients[from_slot].scan_table(tid)
                # the whole table moves; the old namespace's copy is
                # tombstoned so stale reads cannot resurrect it
                if rows:
                    r1 = await self.clients[to_slot].ingest_table(
                        tid, rows, min_epoch=min_epoch)
                    r2 = await self.clients[from_slot].ingest_table(
                        tid, [(k, None) for k, _v in rows],
                        min_epoch=min_epoch)
                    handoff_max = max(handoff_max, int(r1["epoch"]),
                                      int(r2["epoch"]))
        if handoff_max:
            self.loop.advance_epoch_to(handoff_max)
        await self._redeploy_with_fresh_actors(job, {frag_idx: to_slots})
        for j in codomain:
            if j is not job:
                # stopped-with-the-domain siblings come back too
                await self._redeploy_with_fresh_actors(j, {})

    def _codomain_jobs(self, job: JobDeployment) -> List[JobDeployment]:
        """Every deployed job sharing `job`'s barrier domain (itself
        included). The state handoff seals the worker stores above the
        coordinator floor, so every job whose actors could still flush
        below that fence must stop — and redeploy — with it."""
        if self._plane is None:
            return [job]
        dom = self._plane.domain_of_job(job.name)
        if dom is None:
            return [job]
        return [self.jobs[n] for n in self._plane.jobs_of_domain(dom)
                if n in self.jobs]

    async def _stop_and_align(self, job: JobDeployment) -> None:
        """Stop the job's WHOLE DOMAIN at a barrier and push the
        coordinator's commit decision to every worker: the stop
        barrier's epoch is committed on the COORDINATOR but pipelines
        to workers on the next inject — without the push, a handoff
        scan would miss rows born in that epoch and leave them to
        resurrect on the old worker when its staged SST commits later.
        Domain-wide (not just this job): the handoff's worker-side
        seal fences everything below its ingest epochs, and a still-
        running sibling job would have its next flush rejected under
        that fence — stopped siblings have nothing pending, so the
        fence is safe."""
        await self.loop.inject_and_collect(
            force_checkpoint=True,
            mutation=StopMutation(
                self._stop_set(*self._codomain_jobs(job))))
        floor = self.store.committed_epoch()
        for c in self.clients:
            await c.call({"cmd": "recover_store", "epoch": floor})

    async def _redeploy_with_fresh_actors(
            self, job: JobDeployment,
            replaced: Dict[int, List[int]]) -> None:
        """Redeploy every fragment with fresh actor ids (the stopped
        ones are gone from the workers); `replaced` overrides slot
        lists per fragment index."""
        for fi in range(len(job.graph.fragments)):
            slots = replaced.get(
                fi, [s for _a, s in job.placements[fi]])
            job.placements[fi] = [(self._fresh_actor(), s)
                                  for s in slots]
        await self._deploy_job(job)
        if self._plane is not None:
            # the domain's actor filter must name the FRESH actor ids
            # or the redeployed fragments never see another barrier
            self._plane.remove_job(job.name)
            dom = self._plane.assign_job(job.name,
                                         set(job.domain_keys),
                                         sender_ids=(),
                                         expected_ids=(),
                                         actor_ids=job.actor_ids())
            # the handoff ingests committed worker-side ABOVE the
            # coordinator floor — the fresh domain's first barrier
            # must read at/above them, not at the stale floor
            self._plane.advance_domain_to(
                dom, self._plane.last_allocated)
            await self._rewire_domains()

    def _fresh_actor(self) -> int:
        aid = self._next_actor
        self._next_actor += 1
        return aid


def _fragment_table_ids(frag: Fragment) -> List[int]:
    """Every state-table id a fragment's nodes own (the state that must
    move with it)."""
    out: List[int] = []
    for n in frag.nodes:
        op = n["op"]
        if op == "source" and n.get("split_table_id") is not None:
            out.append(int(n["split_table_id"]))
        elif op == "hash_agg":
            out.append(int(n["table_id"]))
            out += [int(v) for v in
                    (n.get("dedup_table_ids") or {}).values()]
            out += [int(v) for v in
                    (n.get("minput_table_ids") or {}).values()]
        elif op == "hash_join":
            out += [int(n["left_table_id"]), int(n["right_table_id"])]
        elif op == "materialize":
            out.append(int(n["table_id"]))
        elif op in ("top_n", "over_window", "eowc_gate", "dedup",
                    "dynamic_filter"):
            out.append(int(n["table_id"]))
        elif op == "backfill":
            out.append(int(n["progress_table_id"]))
        elif op == "watermark_filter" and n.get("table_id") is not None:
            out.append(int(n["table_id"]))
    return out
