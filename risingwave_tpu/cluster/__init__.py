"""Multi-process deployment: coordinator + worker nodes.

Reference parity: the meta/compute-node split (src/compute/src/server.rs:85
compute_node_serve, proto/stream_service.proto InjectBarrier/BarrierComplete,
proto/task_service.proto ExchangeService) — collapsed to two roles over two
TCP planes: stream/remote.py carries data (credit-based exchange), a JSON
control channel carries deploy/inject/stop (the gRPC services' verbs
without protobuf — the wire schema is the next increment).
"""

from risingwave_tpu.cluster.coordinator import WorkerClient, WorkerHandle
