"""DistFrontend: SQL session over an N-worker cluster.

Reference parity: the frontend node talking to meta + compute nodes —
handler/create_mv.rs:147 (plan → fragment → deploy via DdlService) and
the distributed batch read path (scheduler/distributed/stage.rs,
RowSeqScan per node + exchange-gather). TPU re-design: CREATE
MATERIALIZED VIEW plans on the coordinator with the SAME StreamPlanner
the in-process session uses, then the fragmenter serializes the
executor tree to plan IR, cuts it at hash exchanges, and the cluster
scheduler lands the fragments on worker processes. SELECT gathers each
referenced MV's committed rows from every worker namespace into a
snapshot view and runs the ordinary batch planner over it.
"""

from __future__ import annotations

import asyncio
import bisect
from typing import Dict, List, Optional, Union

from risingwave_tpu.cluster.scheduler import Cluster
from risingwave_tpu.frontend import ast
from risingwave_tpu.meta.supervisor import RecoveryStormError
from risingwave_tpu.frontend.catalog import Catalog, MvCatalog
from risingwave_tpu.frontend.fragmenter import Fragmenter
from risingwave_tpu.frontend.planner import (
    PlanError, StreamPlanner, plan_batch, source_schema,
)
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.actor import LocalBarrierManager

Rows = List[tuple]


class ClusterStoreView:
    """Read-only store over rows gathered from worker namespaces —
    batch executors (RowSeqScan via StorageTable) read it like any
    state store. Tables must be prefetched before the sync read."""

    def __init__(self, cluster: Cluster):
        self.cluster = cluster
        self._tables: Dict[int, List[tuple]] = {}   # tid → [(k, row)]

    async def prefetch(self, table_id: int) -> None:
        self._tables[table_id] = await self.cluster.scan_table(table_id)

    def committed_epoch(self) -> int:
        return self.cluster.store.committed_epoch()

    def get(self, table_id: int, key: bytes, epoch: int):
        rows = self._tables.get(table_id, [])
        i = bisect.bisect_left(rows, (key,))
        if i < len(rows) and rows[i][0] == key:
            return rows[i][1]
        return None

    def iter(self, table_id: int, epoch: int, start=None, end=None,
             reverse: bool = False):
        rows = self._tables.get(table_id, [])
        out = [(k, v) for k, v in rows
               if (start is None or k >= start)
               and (end is None or k < end)]
        return iter(reversed(out) if reverse else out)


class DistFrontend:
    """One SQL session driving an N-worker cluster."""

    def __init__(self, root: str, n_workers: int = 2,
                 parallelism: Optional[int] = None,
                 rate_limit: Optional[int] = 8,
                 min_chunks: Optional[int] = None,
                 barrier_timeout_s: Optional[float] = None,
                 epoch_pipeline: bool = True):
        self.cluster = Cluster(root, n_workers,
                               barrier_timeout_s=barrier_timeout_s,
                               epoch_pipeline=epoch_pipeline)
        self.catalog = Catalog()
        self.parallelism = parallelism or n_workers
        self.rate_limit = rate_limit
        self.min_chunks = min_chunks
        self.last_select_schema = None
        # chunk coalescing knobs — same surface as the in-process
        # session (no-drift contract): the planner's keyed-input
        # coalescers AND the scheduler's merge-node re-coalescing both
        # read them (SET stream_chunk_target_rows = 0 disables both)
        from risingwave_tpu.stream.coalesce import (
            DEFAULT_MAX_CHUNKS, DEFAULT_TARGET_ROWS,
        )
        self.chunk_target_rows = DEFAULT_TARGET_ROWS
        self.coalesce_linger_chunks = DEFAULT_MAX_CHUNKS
        # unified state-tiering cap (state/tier.py): the planner stamps
        # it on agg executors and the fragmenter ships it in the IR, so
        # WORKER fragments rebuild with the same memory governance.
        # (The soft-limit var governs the coordinator process only —
        # each worker process has its own MemoryContext.)
        self.state_tier_cap = None
        # name → (select AST, eowc): FROM <mv> inlines the view's
        # definition (distributed MV-on-MV by view expansion)
        self._mv_selects = {}
        # session vars (shared impl with the in-process session —
        # session_vars.py; parallelism is the distributed knob).
        # stream_rewrite_rules rides the same surface as
        # stream_chunk_target_rows: SET here, honored at CREATE time
        from risingwave_tpu.frontend.opt import parse_fusion, parse_rules
        from risingwave_tpu.frontend.session_vars import SessionVars
        from risingwave_tpu.meta.autoscaler import parse_autoscale
        from risingwave_tpu.meta.compaction import (
            parse_compaction as _parse_compaction,
        )
        from risingwave_tpu.stream.costs import (
            parse_costs as _parse_costs,
        )
        from risingwave_tpu.utils.ledger import parse_ledger
        from risingwave_tpu.utils.spans import parse_trace
        self.session_vars = SessionVars(
            self, {"streaming_rate_limit": "rate_limit",
                   "streaming_min_chunks": "min_chunks",
                   "parallelism": "parallelism",
                   "state_tier_cap": "state_tier_cap",
                   "state_tier_soft_limit_mb":
                       "state_tier_soft_limit_mb",
                   "stream_chunk_target_rows": "chunk_target_rows",
                   "stream_coalesce_linger_chunks":
                       "coalesce_linger_chunks"},
            {"stream_rewrite_rules": "all",
             # elastic control loop (meta/autoscaler.py): off by
             # default — scaling actions are topology changes an
             # operator opts into; the serving heartbeat ticks the
             # loop while this is on
             "stream_autoscale": "off",
             # fragment fusion (opt/fusion.py). Distributed deploys
             # fuse at ANY parallelism (ISSUE 10): the hash-exchange
             # cut ships raw rows dispatched on key columns mapped
             # back through the absorbed run; runs whose keys don't
             # map to raw refs stay interpretive (rule-side refusal)
             "stream_fusion": "on",
             # epoch-causal tracing: the SET fans out to every worker
             # over the control channel (same on/off everywhere, or a
             # drained trace would have holes per process)
             "stream_trace": "on",
             # epoch phase ledger (utils/ledger.py): fans out like
             # stream_trace — a cross-process merge must be all-on or
             # all-off
             "stream_ledger": "on",
             # cost & skew attribution (ISSUE 16): per-MV cost books,
             # topology upkeep and hot-key sketches; fans out like
             # stream_ledger
             "stream_costs": "on",
             # compaction arm (ISSUE 19): 'dedicated' provisions the
             # compactor role + CompactionManager (one namespace per
             # worker slot) and moves every merge off the serving path
             "storage_compaction": "inline"},
            validators={"stream_rewrite_rules": parse_rules,
                        "stream_fusion": parse_fusion,
                        "stream_trace": parse_trace,
                        "stream_ledger": parse_ledger,
                        "stream_costs": _parse_costs,
                        "storage_compaction": _parse_compaction,
                        "stream_autoscale": parse_autoscale})
        # the elastic control loop (created lazily on SET
        # stream_autoscale=on; ticked by run_heartbeat while on)
        self.autoscaler = None
        # fragment-graph stats of the last deployed job (exchange
        # hops, exchanged lane widths) — bench + tests read this to
        # see what the rewrite engine bought
        self.last_plan_stats: Optional[dict] = None
        # serializes barrier rounds between DDL, step(), SELECT
        # snapshots and the background heartbeat (inject_and_collect
        # is not reentrant; a heartbeat between per-table scans would
        # tear a cross-MV snapshot)
        self._barrier_lock = asyncio.Lock()

    # same surface as the in-process session (no-drift contract);
    # governs the COORDINATOR process's MemoryContext
    @property
    def state_tier_soft_limit_mb(self) -> int:
        from risingwave_tpu.utils import memory as _mem
        sl = _mem.GLOBAL.soft_limit
        return 0 if sl is None else int(sl) >> 20

    @state_tier_soft_limit_mb.setter
    def state_tier_soft_limit_mb(self, v) -> None:
        from risingwave_tpu.utils import memory as _mem
        _mem.GLOBAL.soft_limit = None if not v else int(v) << 20

    async def start(self) -> None:
        await self.cluster.start()

    async def close(self) -> None:
        await self.cluster.stop()

    async def step(self, n: int = 1) -> None:
        async with self._barrier_lock:
            await self.cluster.step(n)
            # dedicated compaction: settle/dispatch under the same
            # lock a rescale or recovery would hold — an apply never
            # interleaves a topology change
            await self.cluster.compaction_tick()

    async def recover(self) -> None:
        async with self._barrier_lock:
            await self.cluster.recover()

    async def supervised_recover(self, exc: BaseException):
        """Classify `exc` and run the graduated recovery ladder (the
        chaos harness and external drivers share the serving loop's
        path); returns the recorded RecoveryEvent."""
        async with self._barrier_lock:
            return await self.cluster.supervised_recover(exc)

    def _autoscale_on(self) -> bool:
        from risingwave_tpu.meta.autoscaler import parse_autoscale
        return (self.autoscaler is not None
                and self.autoscaler.enabled
                and parse_autoscale(
                    self.session_vars.get("stream_autoscale")))

    async def run_heartbeat(self, interval_s: float = 0.25) -> None:
        """Supervised serving loop (server deployments): each beat
        steps one barrier and ticks worker liveness; a failed round
        feeds the RecoverySupervisor — classify, then the cheapest
        graduated response (absorb / respawn dead slots in place /
        full kill-and-redeploy), with bounded attempts and jittered
        backoff between consecutive recoveries. The only way out is a
        RecoveryStormError: the recovery budget exhausted without a
        healthy round — loud and terminal, never a silent loop and
        never the old recover-once-then-die."""
        import sys
        import traceback
        self.cluster.enable_liveness()
        try:
            while True:
                await asyncio.sleep(interval_s)
                async with self._barrier_lock:
                    try:
                        await self.cluster.step(1)
                        self.cluster.supervisor.note_healthy()
                        if self.autoscaler is not None:
                            # a clean round closes the autoscaler's
                            # storm window too (only after a SUCCESSFUL
                            # action — rollbacks keep the backoff)
                            self.autoscaler.note_healthy()
                        if self._autoscale_on():
                            # elastic control loop (ISSUE 15): signals
                            # → decision → guarded rescale, inside the
                            # barrier lock so a concurrent ALTER queues
                            # behind the action instead of interleaving
                            await self.autoscaler.tick()
                        await self.cluster.compaction_tick()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — classified
                        try:
                            await self.cluster.supervised_recover(e)
                        except asyncio.CancelledError:
                            raise
                        except RecoveryStormError:
                            raise
                        except Exception as rexc:  # noqa: BLE001
                            # a recovery that itself failed is already
                            # recorded (ok=False); the next beat
                            # reclassifies the still-broken state —
                            # the storm gate bounds this loop, not
                            # first-failure death
                            print("recovery attempt failed "
                                  f"(will reclassify): {rexc!r}",
                                  file=sys.stderr)
                await self.cluster.liveness_tick()
        except asyncio.CancelledError:
            raise
        except BaseException:
            print("serving heartbeat terminated:", file=sys.stderr)
            traceback.print_exc()
            raise

    # -- statements -------------------------------------------------------
    async def execute(self, sql: str) -> Union[Rows, str]:
        from risingwave_tpu.frontend.parser import parse_many

        result: Union[Rows, str] = "OK"
        for _text, stmt in parse_many(sql):
            result = await self._run(stmt)
        return result

    async def _run(self, stmt) -> Union[Rows, str]:
        self.last_select_schema = None
        if isinstance(stmt, ast.CreateSource):
            schema = source_schema(stmt.options, stmt.columns)
            self.catalog.add_source(stmt.name, schema, stmt.options)
            return "CREATE_SOURCE"
        if isinstance(stmt, ast.CreateMaterializedView):
            return await self._create_mv(stmt)
        if isinstance(stmt, ast.DropMaterializedView):
            return await self._drop_mv(stmt)
        if isinstance(stmt, ast.CreateSink):
            return await self._create_sink(stmt)
        if isinstance(stmt, ast.DropSink):
            return await self._drop_sink(stmt)
        if isinstance(stmt, ast.SetVar):
            self.session_vars.set(stmt.name, stmt.value)
            if stmt.name == "stream_trace":
                from risingwave_tpu.utils import spans as _spans
                on = _spans.parse_trace(
                    self.session_vars.get("stream_trace"))
                _spans.set_enabled(on)
                await self.cluster.set_trace(on)
            if stmt.name == "stream_ledger":
                from risingwave_tpu.utils import ledger as _ledger
                on = _ledger.parse_ledger(
                    self.session_vars.get("stream_ledger"))
                _ledger.set_enabled(on)
                await self.cluster.set_ledger(on)
            if stmt.name == "stream_costs":
                from risingwave_tpu.stream import costs as _mvcosts
                on = _mvcosts.parse_costs(
                    self.session_vars.get("stream_costs"))
                _mvcosts.set_enabled(on)
                await self.cluster.set_costs(on)
            if stmt.name == "storage_compaction":
                # fans to every worker + (de)provisions the compactor
                # role; serialized with barrier rounds so the flip
                # cannot interleave a commit with a manager drain
                async with self._barrier_lock:
                    await self.cluster.set_compaction(
                        self.session_vars.get("storage_compaction"))
            if stmt.name == "stream_autoscale":
                from risingwave_tpu.meta.autoscaler import (
                    Autoscaler, parse_autoscale,
                )
                if parse_autoscale(
                        self.session_vars.get("stream_autoscale")):
                    if self.autoscaler is None:
                        self.autoscaler = Autoscaler(self.cluster)
                    # re-enabling after a storm is an explicit
                    # operator decision — reset the disabled latch
                    # AND the exhausted backoff budget (a still-maxed
                    # gate would re-raise the storm on the next
                    # decision without attempting a single rescale)
                    self.autoscaler.reset_storm()
            return "SET"
        if isinstance(stmt, ast.Show):
            if stmt.what == "var:all":
                return self.session_vars.show_all()
            if stmt.what.startswith("var:"):
                name = stmt.what[4:].lower()
                if not self.session_vars.known(name):
                    raise PlanError("unrecognized configuration "
                                    f"parameter {name!r}")
                return [(self.session_vars.get(name),)]
            if stmt.what == "sources":
                return [(n,) for n in sorted(self.catalog.sources)]
            if stmt.what == "sinks":
                return [(n,) for n in sorted(self.catalog.sinks)]
            if stmt.what == "tables":
                return [(n,) for n, m in sorted(self.catalog.mvs.items())
                        if m.is_table]
            return [(n,) for n, m in sorted(self.catalog.mvs.items())
                    if not m.is_table]
        if isinstance(stmt, ast.Explain):
            from risingwave_tpu.frontend.opt import explain_with_rewrite
            planner = StreamPlanner(
                self.catalog, MemoryStateStore(),
                LocalBarrierManager(), definition="", mesh=None,
                actors={}, dist_parallelism=self.parallelism,
                inline_mvs=self._mv_selects,
                chunk_target_rows=self.chunk_target_rows,
                coalesce_linger_chunks=self.coalesce_linger_chunks)
            plan = planner.plan("__explain__", stmt.select, actor_id=0,
                                rate_limit=self.rate_limit,
                                min_chunks=self.min_chunks)
            from risingwave_tpu.frontend.opt import parse_fusion
            return explain_with_rewrite(
                plan.consumer,
                self.session_vars.get("stream_rewrite_rules"),
                fusion=parse_fusion(
                    self.session_vars.get("stream_fusion")),
                dist_parallelism=self.parallelism)
        if isinstance(stmt, ast.AlterParallelism):
            return await self._alter_parallelism(stmt)
        if isinstance(stmt, ast.Flush):
            await self.step(1)
            return "FLUSH"
        if isinstance(stmt, ast.Select):
            return await self._select(stmt)
        raise PlanError(
            f"unhandled statement on the distributed session: {stmt!r}")

    async def _create_mv(self, stmt: ast.CreateMaterializedView) -> str:
        """Plan with the ordinary StreamPlanner (against throwaway
        runtime objects), fragment the executor tree, deploy across the
        cluster, then run the activation barrier."""
        self.catalog._check_free(stmt.name)
        if getattr(stmt, "emit_on_window_close", False):
            raise PlanError("EMIT ON WINDOW CLOSE is not distributed "
                            "yet — use the in-process session")
        planner = StreamPlanner(self.catalog, MemoryStateStore(),
                                LocalBarrierManager(), definition="",
                                mesh=None, actors={},
                                dist_parallelism=self.parallelism,
                                inline_mvs=self._mv_selects,
                                chunk_target_rows=self.chunk_target_rows,
                                coalesce_linger_chunks=self
                                .coalesce_linger_chunks,
                                state_tier_cap=self.state_tier_cap
                                or None)
        plan = planner.plan(stmt.name, stmt.select, actor_id=0,
                            rate_limit=self.rate_limit,
                            min_chunks=self.min_chunks)
        # executor-graph rewrite before lowering (same engine as the
        # in-process session); the fragment-graph pass below then
        # elides exchanges on the shipped plan IR
        from risingwave_tpu.frontend.opt import (
            apply_rewrites, parse_fusion,
        )
        rules = self.session_vars.get("stream_rewrite_rules")
        # fusion at ANY parallelism since ISSUE 10: the fragmenter cuts
        # below an absorbed run on raw-mapped key columns, and the rule
        # refuses runs whose keys don't map (opt/fusion.py)
        fusion = parse_fusion(self.session_vars.get("stream_fusion"))
        apply_rewrites(plan, rules, label=stmt.name, fusion=fusion,
                       dist_parallelism=self.parallelism)
        if plan.attaches:
            # every FROM <mv> should have inlined (the dict holds all
            # session-created views); a chain attach here means a
            # catalog/selects mismatch — refuse rather than ship a
            # graph with dangling attach edges
            raise PlanError(
                "internal: distributed plan produced chain attaches "
                "(view not inlined?) — cannot deploy")
        graph = Fragmenter(
            self.parallelism,
            merge_coalesce_rows=self.chunk_target_rows,
            merge_coalesce_chunks=self.coalesce_linger_chunks
        ).lower(plan.consumer)
        from risingwave_tpu.frontend.opt import (
            fragment_plan_stats, rewrite_fragment_graph,
        )
        graph, _elided = rewrite_fragment_graph(graph, rules,
                                                label=stmt.name)
        self.last_plan_stats = fragment_plan_stats(graph)
        async with self._barrier_lock:
            # domain anchors: the job's own name + every source/MV it
            # reads — shared-source fan-outs and view-expanded chains
            # align in one barrier domain, disjoint jobs in their own
            await self.cluster.deploy_graph(
                stmt.name, graph,
                domain_keys={stmt.name, *plan.mv.dependent_sources})
            await self.cluster.step(1)     # activation barrier
        self.catalog.add_mv(plan.mv)
        # freshness lineage on the COORDINATOR tracker: the worker
        # fragments report raw parts; the merge joins them under this
        # registration (drain_freshness). MV deps resolve to their
        # SOURCES transitively, same as the in-process session — a
        # chained MV bound to no frontier would report constant
        # zero-lag samples
        from risingwave_tpu.stream.freshness import FRESHNESS
        srcs, seen = [], set()

        def _walk_dep(d):
            if d in seen:
                return
            seen.add(d)
            if d in self.catalog.sources:
                srcs.append(d)
            elif d in self.catalog.mvs:
                for dd in self.catalog.mvs[d].dependent_sources:
                    _walk_dep(dd)

        for dep in plan.mv.dependent_sources:
            _walk_dep(dep)
        FRESHNESS.register_mv(stmt.name, srcs,
                              self.cluster.domain_of_job(stmt.name))
        self._mv_selects[stmt.name] = (
            stmt.select, getattr(stmt, "emit_on_window_close", False))
        return "CREATE_MATERIALIZED_VIEW"

    async def _alter_parallelism(self, stmt) -> str:
        """ALTER MATERIALIZED VIEW <name> SET PARALLELISM n on the
        cluster: every vnode-rescalable fragment of the job rescales
        to n actors round-robined over the worker slots with the
        vnode-sliced state handoff (scale.rs:717 across processes),
        and filelog SOURCE fragments rescale by split reassignment
        (partitions rebalance over the new actors; offsets resume
        exactly). Both paths run the guarded-rescale protocol: a
        mid-way failure rolls the domain back to the prior topology
        (visible in rw_recovery) instead of leaving it half-deployed,
        and a concurrent topology change gets a clear 'rescale in
        progress' error, never an interleaved redeploy."""
        name, n = stmt.name, stmt.parallelism
        job = self.cluster.jobs.get(name)
        if job is None:
            raise PlanError(f"unknown materialized view {name!r}")
        targets = [
            (fi, self.cluster._source_rescalable(f))
            for fi, f in enumerate(job.graph.fragments)
            if self.cluster._rescalable(f)
            or self.cluster._source_rescalable(f)]
        if not targets:
            raise PlanError(
                f"{name!r} has no rescalable fragment")
        async with self._barrier_lock:
            # one stop-the-world cycle per fragment; jobs today carry
            # at most a couple of rescalable fragments — batch into a
            # single stop/handoff/redeploy if that changes
            for fi, is_source in targets:
                to_slots = [(fi + k) % self.cluster.n for k in range(n)]
                if is_source:
                    await self.cluster.rescale_source_fragment(
                        name, fi, to_slots)
                else:
                    await self.cluster.rescale_fragment(name, fi,
                                                        to_slots)
        if name in self.catalog.sinks:
            # sink jobs rescale through the same guarded path (the
            # sink node is stateless; redeploy re-stamps writer=rank
            # and n_writers on every actor) — keep the coordinator's
            # writer count and the catalog in step for telemetry
            self.catalog.sinks[name].n_writers = n
            sk = self.cluster.sinks.sink(name)
            if sk is not None:
                sk.n_writers = n
        return "ALTER_MATERIALIZED_VIEW"

    async def _drop_mv(self, stmt: ast.DropMaterializedView) -> str:
        if stmt.name not in self.catalog.mvs:
            if stmt.if_exists:
                return "DROP_MATERIALIZED_VIEW"
            raise PlanError(f"unknown materialized view {stmt.name!r}")
        dependents = [m.name for m in self.catalog.mvs.values()
                      if stmt.name in m.dependent_sources]
        if dependents:
            raise PlanError(f"cannot drop MV {stmt.name!r}: depended "
                            f"on by {dependents}")
        async with self._barrier_lock:
            await self.cluster.drop_job(stmt.name)
        del self.catalog.mvs[stmt.name]
        self._mv_selects.pop(stmt.name, None)
        # central series-lifecycle purge (freshness, costs, hot keys,
        # topology): coordinator-side books — including drained worker
        # copies — die with the job so no {mv=...} series lingers
        from risingwave_tpu.stream.costs import purge_mv_series
        purge_mv_series(stmt.name)
        return "DROP_MATERIALIZED_VIEW"

    async def _create_sink(self, stmt: ast.CreateSink) -> str:
        """CREATE SINK on the cluster: plan with the ordinary
        StreamPlanner (FROM <mv> inlines by view expansion, same as
        distributed MVs), lower the sink as a colocated fragment node,
        and register the encoder on the COORDINATOR's SinkCoordinator
        with deferred=False — workers stage their own segments
        synchronously at barrier passage (plan_ir builds inline
        CoordinatedSinkExecutors), the coordinator only runs the
        commit/recovery half off the checkpoint floor."""
        from risingwave_tpu.frontend.catalog import SinkCatalog
        from risingwave_tpu.frontend.planner import validate_sink_options
        self.catalog._check_free(stmt.name)
        validate_sink_options(stmt.options)
        connector = stmt.options.get("connector", "filelog").lower()
        if connector != "epochlog":
            raise PlanError(
                "distributed sinks require connector='epochlog' (the "
                "epoch-segment exactly-once sink); legacy writer sinks "
                "are in-process only")
        planner = StreamPlanner(self.catalog, MemoryStateStore(),
                                LocalBarrierManager(), definition="",
                                mesh=None, actors={},
                                dist_parallelism=self.parallelism,
                                inline_mvs=self._mv_selects,
                                chunk_target_rows=self.chunk_target_rows,
                                coalesce_linger_chunks=self
                                .coalesce_linger_chunks,
                                state_tier_cap=self.state_tier_cap
                                or None)
        plan = planner.plan_sink(stmt.select, stmt.options, actor_id=0,
                                 rate_limit=self.rate_limit,
                                 min_chunks=self.min_chunks,
                                 sink_name=stmt.name,
                                 append_only=stmt.append_only,
                                 coordinator=None)
        from risingwave_tpu.frontend.opt import (
            apply_rewrites, parse_fusion,
        )
        rules = self.session_vars.get("stream_rewrite_rules")
        fusion = parse_fusion(self.session_vars.get("stream_fusion"))
        apply_rewrites(plan, rules, label=stmt.name, fusion=fusion,
                       dist_parallelism=self.parallelism)
        if plan.attaches:
            raise PlanError(
                "internal: distributed sink plan produced chain "
                "attaches (view not inlined?) — cannot deploy")
        graph = Fragmenter(
            self.parallelism,
            merge_coalesce_rows=self.chunk_target_rows,
            merge_coalesce_chunks=self.coalesce_linger_chunks
        ).lower(plan.consumer)
        from risingwave_tpu.frontend.opt import (
            fragment_plan_stats, rewrite_fragment_graph,
        )
        graph, _elided = rewrite_fragment_graph(graph, rules,
                                                label=stmt.name)
        self.last_plan_stats = fragment_plan_stats(graph)
        n_writers = max(
            (f.parallelism for f in graph.fragments
             if any(n.get("op") == "sink" for n in f.nodes)),
            default=1)
        # register BEFORE the activation barrier: the first checkpoint
        # after deploy may already carry sink rows, and commit_upto on
        # the coordinator must know the sink exists to manifest them.
        # floor=-1: a fresh CREATE truncates any leftover staging under
        # the same path (prior generation's uncommitted epochs) and
        # promotes nothing.
        self.cluster.sinks.register(stmt.name, plan.encoder,
                                    n_writers=n_writers,
                                    deferred=False, floor=-1)
        try:
            async with self._barrier_lock:
                await self.cluster.deploy_graph(
                    stmt.name, graph,
                    domain_keys={stmt.name, *plan.deps})
                await self.cluster.step(1)     # activation barrier
        except BaseException:
            self.cluster.sinks.unregister(stmt.name)
            raise
        self.catalog.add_sink(SinkCatalog(
            stmt.name, 0, dict(stmt.options),
            dependent_sources=plan.deps, mode=plan.mode,
            n_writers=n_writers))
        return "CREATE_SINK"

    async def _drop_sink(self, stmt: ast.DropSink) -> str:
        if stmt.name not in self.catalog.sinks:
            if stmt.if_exists:
                return "DROP_SINK"
            raise PlanError(f"unknown sink {stmt.name!r}")
        async with self._barrier_lock:
            await self.cluster.drop_job(stmt.name)
        # committed manifests + segments stay on disk (the sink's
        # output is the product); only the coordinator registration
        # dies with the job
        self.cluster.sinks.unregister(stmt.name)
        del self.catalog.sinks[stmt.name]
        return "DROP_SINK"

    async def drain_trace(self) -> int:
        """Merge every worker's recorded epoch-trace spans into the
        coordinator's flight recorder (tagged worker-k); returns the
        number of spans ingested."""
        return await self.cluster.drain_trace()

    async def drain_ledger(self) -> int:
        """Merge every worker's phase-ledger accumulators into the
        coordinator's sealed records (the distributed conservation
        story: worker host/device time folds into the epoch intervals
        the coordinator measured); returns epochs ingested."""
        return await self.cluster.drain_ledger()

    async def _select(self, sel: ast.Select) -> Rows:
        from risingwave_tpu.batch import collect

        referenced = self._referenced_system_tables(sel)
        if "rw_epoch_trace" in referenced:
            # the trace table serves the MERGED cluster view: pull
            # worker spans in before the batch scan reads the tracer
            await self.drain_trace()
        if referenced & {"rw_metrics_history", "rw_kernel_costs"}:
            # same discipline for the phase ledger: fold worker books
            # into the sealed records before anything reads them (the
            # conservation residuals recompute on merge)
            await self.drain_ledger()
        if referenced & {"rw_mv_freshness", "rw_metrics_history"}:
            # freshness parts live on the workers (source + materialize
            # fragments): merge them before the tracker serves rows
            await self.cluster.drain_freshness()
        if referenced & {"rw_bottlenecks", "rw_actor_utilization",
                         "rw_mv_costs", "rw_hot_keys",
                         "rw_state_topology"}:
            # the tricolor + walker + attribution surfaces live where
            # the chains live (worker processes): pull their
            # snapshots/books before the read
            await self.cluster.drain_signals()
        if "rw_mv_costs" in referenced:
            # cost rows join the ledgered device books — fold worker
            # ledgers too so the per-MV split reads against merged
            # totals
            await self.drain_ledger()
        view = ClusterStoreView(self.cluster)
        # one consistent snapshot: the barrier lock keeps the
        # heartbeat from committing an epoch between per-table scans
        async with self._barrier_lock:
            await asyncio.gather(
                *(view.prefetch(tid)
                  for tid in self._referenced_table_ids(sel)))
        loop = getattr(self.cluster, "loop", None)
        ex = plan_batch(sel, self.catalog, view,
                        view.committed_epoch(),
                        profiler=getattr(loop, "profiler", None))
        self.last_select_schema = ex.schema
        return collect(ex)

    @staticmethod
    def _referenced_system_tables(sel: ast.Select) -> set:
        """Lower-cased table names a SELECT touches (FROM + JOINs +
        subqueries) — the drain-before-read triggers."""
        names = set()

        def from_item(item):
            if item is None:
                return
            if isinstance(item, ast.Subquery):
                walk(item.select)
                return
            name = getattr(item, "name", None) or getattr(
                getattr(item, "table", None), "name", None)
            if name is not None:
                names.add(str(name).lower())

        def walk(s):
            from_item(s.from_item)
            for jn in getattr(s, "joins", []):
                from_item(jn.item)

        walk(sel)
        return names

    def _referenced_table_ids(self, sel: ast.Select) -> List[int]:
        """MV table ids a SELECT touches (FROM + JOINs + subqueries)."""
        out: List[int] = []

        def from_item(item):
            if item is None:
                return
            if isinstance(item, ast.Subquery):
                walk(item.select)
                return
            name = getattr(item, "name", None) or getattr(
                getattr(item, "table", None), "name", None)
            if name is None:
                return
            obj = self.catalog.mvs.get(name)
            if isinstance(obj, MvCatalog):
                out.append(obj.table_id)

        def walk(s):
            from_item(s.from_item)
            for jn in getattr(s, "joins", []):
                from_item(jn.item)

        walk(sel)
        return out
