"""Vectorized expression tree.

Reference parity: src/expr/src/expr/mod.rs:74 (`Expression::eval(&DataChunk)
-> ArrayRef`), build.rs (tree construction), vector_op/ (scalar kernels).

TPU-first notes:
- ``eval`` returns a ``Column`` whose values cover the chunk's full static
  capacity; invisible/padding rows compute garbage that is never observed
  (XLA loves branchless full-width math; masking happens at the consumer).
- Nulls: SQL three-valued logic via optional validity arrays. Arithmetic
  propagates null; AND/OR implement Kleene logic.
- DECIMAL is scaled int64: mul/div rescale; add/sub/compare are plain int
  ops, so money aggregation is retraction-exact.
- Division by zero yields NULL (documented divergence: the reference raises
  ExprError::DivisionByZero and poisons the whole chunk; a streaming NULL
  keeps the pipeline alive and is what our .slt harness asserts).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common.chunk import Column, DataChunk, get_xp
import decimal

from risingwave_tpu.common.types import (
    DECIMAL_SCALE,
    DataType,
    Interval,
    decimal_to_scaled,
    scaled_to_decimal,
)

# ---------------------------------------------------------------------------
# type inference helpers


_NUMERIC_ORDER = [
    DataType.INT16, DataType.INT32, DataType.INT64,
    DataType.DECIMAL, DataType.FLOAT32, DataType.FLOAT64,
]


def promote_numeric(lt: DataType, rt: DataType) -> DataType:
    """Binary numeric result type: later in _NUMERIC_ORDER wins."""
    if lt == rt:
        return lt
    for t in (lt, rt):
        if t not in _NUMERIC_ORDER:
            raise TypeError(f"not numeric: {t}")
    return _NUMERIC_ORDER[max(_NUMERIC_ORDER.index(lt),
                              _NUMERIC_ORDER.index(rt))]


_TIME_TYPES = (DataType.DATE, DataType.TIME, DataType.TIMESTAMP,
               DataType.TIMESTAMPTZ)
_INT_TYPES = (DataType.INT16, DataType.INT32, DataType.INT64,
              DataType.SERIAL)


def _promote_comparison(lt: DataType, rt: DataType) -> DataType:
    """Comparison common type: numerics promote; a time type compares
    against integer literals in its physical domain (days / µs), and
    TIMESTAMP against TIMESTAMPTZ (same µs domain). Mixed-unit time
    comparisons (DATE vs TIMESTAMP) are rejected — the physical values
    live in different domains and a raw compare would be garbage."""
    ts_pair = {DataType.TIMESTAMP, DataType.TIMESTAMPTZ}
    if lt in ts_pair and rt in ts_pair:
        return DataType.TIMESTAMP
    if lt in _TIME_TYPES and rt in _TIME_TYPES:
        raise TypeError(
            f"cannot compare {lt.value} with {rt.value} — cast one "
            "side explicitly")
    for a, b in ((lt, rt), (rt, lt)):
        if a in _TIME_TYPES and b in _INT_TYPES:
            return a
    return promote_numeric(lt, rt)


def _parse_timestamp_us(s: str) -> int:
    import datetime
    s = s.strip().replace("T", " ")
    dt = datetime.datetime.fromisoformat(s)
    if dt.tzinfo is not None:
        dt = dt.astimezone(datetime.timezone.utc).replace(tzinfo=None)
    epoch = datetime.datetime(1970, 1, 1)
    return int((dt - epoch).total_seconds() * 1_000_000)


def _cast_one_string(v, dst: DataType):
    if v is None:
        return 0
    if dst in (DataType.INT16, DataType.INT32, DataType.INT64,
               DataType.SERIAL):
        return int(v)
    if dst in (DataType.FLOAT32, DataType.FLOAT64):
        return float(v)
    if dst == DataType.DECIMAL:
        return decimal_to_scaled(decimal.Decimal(v))
    if dst == DataType.BOOLEAN:
        return v.strip().lower() in ("t", "true", "1", "yes", "on")
    if dst in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
        return _parse_timestamp_us(v)
    if dst == DataType.DATE:
        import datetime
        return (datetime.date.fromisoformat(v.strip())
                - datetime.date(1970, 1, 1)).days
    if dst == DataType.TIME:
        import datetime
        t = datetime.time.fromisoformat(v.strip())
        return ((t.hour * 60 + t.minute) * 60 + t.second) * 1_000_000 \
            + t.microsecond
    raise TypeError(f"cannot cast string to {dst}")


def _format_to_string(v, src: DataType) -> str:
    """pg text-out for physical values (round-trips _cast_one_string)."""
    import datetime
    if src == DataType.DECIMAL:
        return str(scaled_to_decimal(v))
    if src == DataType.BOOLEAN:
        return "true" if v else "false"
    if src in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ):
        us = int(v)
        base = datetime.datetime(1970, 1, 1) + \
            datetime.timedelta(microseconds=us)
        out = base.isoformat(sep=" ")
        return out + "+00:00" if src == DataType.TIMESTAMPTZ else out
    if src == DataType.DATE:
        return (datetime.date(1970, 1, 1)
                + datetime.timedelta(days=int(v))).isoformat()
    if src == DataType.TIME:
        us = int(v)
        s_, rem = divmod(us, 1_000_000)
        h, r2 = divmod(s_, 3600)
        m, sec = divmod(r2, 60)
        out = f"{h:02d}:{m:02d}:{sec:02d}"
        return out + (f".{rem:06d}" if rem else "")
    return str(v)


def _cast_values(vals, src: DataType, dst: DataType):
    xp = get_xp(vals)
    if src == dst:
        return vals
    if src == DataType.VARCHAR:
        # host object arrays: per-element parse (pg text-in semantics)
        out = [_cast_one_string(v, dst) for v in vals.tolist()]
        return np.asarray(out, dtype=dst.np_dtype)
    if dst == DataType.VARCHAR:
        lst = [_format_to_string(v, src) for v in vals.tolist()]
        out = np.empty(len(lst), dtype=object)
        out[:] = lst
        return out
    if dst == DataType.DECIMAL:
        # overflow detection at the cast boundary (VERDICT r5 weak
        # #6): the scaled int64 domain ends at ~9.2e14 value units —
        # raise instead of silently wrapping. Host (numpy) arrays
        # only: a device-array check would force a sync; every ingest
        # path (connectors, INSERT, string casts) is host-side.
        from risingwave_tpu.common.types import _SCALED_MAX
        lim = _SCALED_MAX // DECIMAL_SCALE
        if src in (DataType.FLOAT32, DataType.FLOAT64):
            if xp is np:
                f = np.asarray(vals, dtype=np.float64)
                # non-finite values (inf/nan) cannot be numeric either
                # — pg raises "cannot convert ... to numeric" too
                bad = ~np.isfinite(f) | (np.abs(f) > float(lim))
                if bad.any():
                    from risingwave_tpu.common.types import (
                        DecimalOverflowError,
                    )
                    raise DecimalOverflowError(
                        f"cast to DECIMAL overflows the int64 "
                        f"fixed-point domain (|value| must stay "
                        f"under {lim}): {f[bad][0]!r}")
            return xp.rint(vals * DECIMAL_SCALE).astype(xp.int64)
        if xp is np:
            v64 = np.asarray(vals).astype(np.int64)
            bad = (v64 > lim) | (v64 < -lim)
            if bad.any():
                from risingwave_tpu.common.types import (
                    DecimalOverflowError,
                )
                raise DecimalOverflowError(
                    f"cast to DECIMAL overflows the int64 fixed-point "
                    f"domain (|value| must stay under {lim}): "
                    f"{int(v64[bad][0])}")
        return vals.astype(xp.int64) * xp.int64(DECIMAL_SCALE)
    if src == DataType.DECIMAL:
        # decimal → float: divide in the destination float dtype
        return vals.astype(dst.dtype) / xp.asarray(DECIMAL_SCALE,
                                                   dtype=dst.dtype)
    return vals.astype(dst.dtype)


def _merge_validity(a: Optional[jnp.ndarray],
                    b: Optional[jnp.ndarray]) -> Optional[jnp.ndarray]:
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _div_trunc(num, den):
    """Integer division truncating toward zero (SQL numeric semantics)."""
    xp = get_xp(num, den)
    q = num // den
    rem = num % den
    neg = (num < 0) != (den < 0)
    return xp.where(neg & (rem != 0), q + 1, q)


# ---------------------------------------------------------------------------
# expression nodes


class Expression:
    """Base: vectorized ``eval(chunk) -> Column`` (expr/mod.rs:74 analog)."""

    return_type: DataType

    def eval(self, chunk: DataChunk) -> Column:
        raise NotImplementedError

    # -- operator sugar for plan construction ---------------------------
    def __add__(self, other):  return BinaryOp("+", self, _wrap(other))
    def __sub__(self, other):  return BinaryOp("-", self, _wrap(other))
    def __mul__(self, other):  return BinaryOp("*", self, _wrap(other))
    def __truediv__(self, other): return BinaryOp("/", self, _wrap(other))
    def __mod__(self, other):  return BinaryOp("%", self, _wrap(other))
    def __eq__(self, other):   return BinaryOp("=", self, _wrap(other))  # type: ignore[override]
    def __ne__(self, other):   return BinaryOp("<>", self, _wrap(other))  # type: ignore[override]
    def __lt__(self, other):   return BinaryOp("<", self, _wrap(other))
    def __le__(self, other):   return BinaryOp("<=", self, _wrap(other))
    def __gt__(self, other):   return BinaryOp(">", self, _wrap(other))
    def __ge__(self, other):   return BinaryOp(">=", self, _wrap(other))
    def __and__(self, other):  return BinaryOp("and", self, _wrap(other))
    def __or__(self, other):   return BinaryOp("or", self, _wrap(other))
    def __invert__(self):      return UnaryOp("not", self)
    def __neg__(self):         return UnaryOp("neg", self)
    __hash__ = object.__hash__


def _wrap(v) -> "Expression":
    return v if isinstance(v, Expression) else Literal.infer(v)


class InputRef(Expression):
    """Column reference by index (expr/expr_input_ref.rs analog)."""

    def __init__(self, index: int, data_type: DataType):
        self.index = index
        self.return_type = data_type

    def eval(self, chunk: DataChunk) -> Column:
        c = chunk.columns[self.index]
        assert c.data_type == self.return_type, (c.data_type, self.return_type)
        return c

    def __repr__(self):
        return f"${self.index}:{self.return_type.name.lower()}"


def col(chunk_schema, name: str) -> InputRef:
    """Convenience: InputRef by column name against a Schema."""
    i = chunk_schema.index_of(name)
    return InputRef(i, chunk_schema[i].data_type)


class Literal(Expression):
    """Constant (expr/expr_literal.rs analog); broadcast at eval."""

    def __init__(self, value, data_type: DataType):
        self.value = value
        self.return_type = data_type

    @staticmethod
    def infer(v) -> "Literal":
        if isinstance(v, bool):
            return Literal(v, DataType.BOOLEAN)
        if isinstance(v, int):
            return Literal(v, DataType.INT64)
        if isinstance(v, float):
            return Literal(v, DataType.FLOAT64)
        if isinstance(v, str):
            return Literal(v, DataType.VARCHAR)
        if isinstance(v, Interval):
            return Literal(v, DataType.INTERVAL)
        if v is None:
            return Literal(None, DataType.INT64)
        import decimal
        if isinstance(v, decimal.Decimal):
            return Literal(v, DataType.DECIMAL)
        raise TypeError(f"cannot infer literal type of {v!r}")

    def _physical(self):
        if self.return_type == DataType.DECIMAL and self.value is not None:
            return decimal_to_scaled(self.value)
        return self.value

    def eval(self, chunk: DataChunk) -> Column:
        cap = chunk.capacity
        dt = self.return_type
        xp = get_xp(chunk.visibility)
        if self.value is None:
            vals = (xp.zeros(cap, dtype=dt.np_dtype) if dt.is_device
                    else np.full(cap, None, dtype=object))
            validity = xp.zeros(cap, dtype=bool)
            return Column(dt, vals, validity)
        if dt.is_device:
            return Column(dt, xp.full(cap, self._physical(),
                                      dtype=dt.np_dtype))
        return Column(dt, np.full(cap, self.value, dtype=object))

    def __repr__(self):
        return f"{self.value!r}:{self.return_type.name.lower()}"


def lit(v, data_type: Optional[DataType] = None) -> Literal:
    if data_type is DataType.DECIMAL and not hasattr(v, "as_tuple"):
        import decimal
        v = decimal.Decimal(str(v)) if v is not None else None
    return Literal.infer(v) if data_type is None else Literal(v, data_type)


_CMP_OPS = {"=", "<>", "<", "<=", ">", ">="}
_ARITH_OPS = {"+", "-", "*", "/", "%"}
_LOGIC_OPS = {"and", "or"}


class BinaryOp(Expression):
    """Arithmetic / comparison / logical binary op (expr_binary_* analog)."""

    def __init__(self, op: str, left: Expression, right: Expression):
        assert op in _CMP_OPS | _ARITH_OPS | _LOGIC_OPS, op
        self.op = op
        self.left = left
        self.right = right
        lt, rt = left.return_type, right.return_type
        if op in _LOGIC_OPS:
            assert lt == DataType.BOOLEAN and rt == DataType.BOOLEAN
            self.return_type = DataType.BOOLEAN
            self._common = DataType.BOOLEAN
        elif op in _CMP_OPS:
            self._common = lt if lt == rt \
                else _promote_comparison(lt, rt)
            self.return_type = DataType.BOOLEAN
        else:
            self._common = lt if lt == rt else promote_numeric(lt, rt)
            if op == "/" and self._common in (
                    DataType.INT16, DataType.INT32, DataType.INT64):
                self._common = DataType.DECIMAL  # SQL: int/int is exact-ish
            self.return_type = self._common

    def eval(self, chunk: DataChunk) -> Column:
        lc = self.left.eval(chunk)
        rc = self.right.eval(chunk)
        if self.op in _LOGIC_OPS:
            return self._eval_logic(lc, rc)
        if not self._common.is_device:
            return self._eval_host_cmp(chunk, lc, rc)
        lv = _cast_values(lc.values, lc.data_type, self._common)
        rv = _cast_values(rc.values, rc.data_type, self._common)
        xp = get_xp(lv, rv)
        validity = _merge_validity(lc.validity, rc.validity)
        op = self.op
        if op in _CMP_OPS:
            fn = {"=": xp.equal, "<>": xp.not_equal, "<": xp.less,
                  "<=": xp.less_equal, ">": xp.greater,
                  ">=": xp.greater_equal}[op]
            return Column(DataType.BOOLEAN, fn(lv, rv), validity)
        if op == "+":
            out = lv + rv
        elif op == "-":
            out = lv - rv
        elif op == "*":
            if self._common == DataType.DECIMAL:
                out = _div_trunc(lv * rv, xp.int64(DECIMAL_SCALE))
            else:
                out = lv * rv
        elif op == "%":
            zero = rv == 0
            safe = xp.where(zero, xp.ones_like(rv), rv)
            if self._common in (DataType.FLOAT32, DataType.FLOAT64):
                out = xp.fmod(lv, safe)  # truncated, sign of dividend
            else:
                # SQL truncated modulo: a - trunc(a/b)*b (sign follows a)
                out = lv - _div_trunc(lv, safe) * safe
            validity = _merge_validity(validity, ~zero)
        else:  # "/"
            zero = rv == 0
            safe = xp.where(zero, xp.ones_like(rv), rv)
            if self._common == DataType.DECIMAL:
                out = _div_trunc(lv * xp.int64(DECIMAL_SCALE), safe)
            else:
                out = lv / safe
            validity = _merge_validity(validity, ~zero)
        return Column(self.return_type, out, validity)

    def _eval_host_cmp(self, chunk: DataChunk, lc: Column,
                       rc: Column) -> Column:
        """Comparisons over host columns (varchar etc.) — numpy object ops."""
        if self.op not in _CMP_OPS:
            raise TypeError(
                f"operator {self.op!r} unsupported for host type "
                f"{self._common}; only comparisons are")
        cap = chunk.capacity
        lv, rv = np.asarray(lc.values), np.asarray(rc.values)
        validity = _merge_validity(lc.validity, rc.validity)
        # Compare only slots where both sides are present — padding and null
        # slots hold None (or stale objects of another type) and must never
        # reach the python comparison operator.
        lnull = lv == None  # noqa: E711  (elementwise)
        rnull = rv == None  # noqa: E711
        vis = np.asarray(chunk.visibility)
        if validity is not None:
            vis = vis & np.asarray(validity)
        ok = vis & ~lnull & ~rnull
        import operator as _op
        fn = {"=": _op.eq, "<>": _op.ne, "<": _op.lt, "<=": _op.le,
              ">": _op.gt, ">=": _op.ge}[self.op]
        res = np.zeros(cap, dtype=bool)
        idx = np.flatnonzero(ok)
        if idx.size:
            res[idx] = np.asarray(fn(lv[idx], rv[idx]), dtype=bool)
        null_any = lnull | rnull
        if null_any.any():
            nv = ~null_any
            validity = nv if validity is None \
                else (np.asarray(validity) & nv)
        return Column(DataType.BOOLEAN, res, validity)

    def _eval_logic(self, lc: Column, rc: Column) -> Column:
        lv, rv = lc.values, rc.values
        xp = get_xp(lv, rv)
        ln = lc.validity if lc.validity is not None else xp.ones_like(lv)
        rn = rc.validity if rc.validity is not None else xp.ones_like(rv)
        if self.op == "and":
            # Kleene: false AND null = false; true AND null = null
            out = lv & rv
            validity = ((ln & rn) | (ln & ~lv) | (rn & ~rv))
        else:
            out = lv | rv
            validity = ((ln & rn) | (ln & lv) | (rn & rv))
        if lc.validity is None and rc.validity is None:
            validity = None
        return Column(DataType.BOOLEAN, out, validity)

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


def and_(*exprs: Expression) -> Expression:
    out = exprs[0]
    for e in exprs[1:]:
        out = BinaryOp("and", out, e)
    return out


def or_(*exprs: Expression) -> Expression:
    out = exprs[0]
    for e in exprs[1:]:
        out = BinaryOp("or", out, e)
    return out


class UnaryOp(Expression):
    def __init__(self, op: str, child: Expression):
        assert op in ("not", "neg", "is_null", "is_not_null"), op
        self.op = op
        self.child = child
        self.return_type = (DataType.BOOLEAN if op in
                            ("not", "is_null", "is_not_null")
                            else child.return_type)

    def eval(self, chunk: DataChunk) -> Column:
        c = self.child.eval(chunk)
        if self.op == "not":
            return Column(DataType.BOOLEAN, ~c.values, c.validity)
        if self.op == "neg":
            return Column(c.data_type, -c.values, c.validity)
        cap = chunk.capacity
        xp = get_xp(c.values)
        present = (xp.ones(cap, dtype=bool) if c.validity is None
                   else c.validity)
        vals = present if self.op == "is_not_null" else ~present
        return Column(DataType.BOOLEAN, vals, None)

    def __repr__(self):
        return f"{self.op}({self.child!r})"


class Cast(Expression):
    """Explicit type conversion (expr_cast analog; physical-domain
    aware: DECIMAL scaled-int64 → float divides out the scale)."""

    def __init__(self, child: Expression, to: DataType):
        self.child = child
        self.return_type = to

    def eval(self, chunk: DataChunk) -> Column:
        c = self.child.eval(chunk)
        if c.data_type == self.return_type:
            return c
        validity = c.validity
        if not c.data_type.is_device:
            # host columns carry NULL as the None OBJECT — derive the
            # mask here or NULL would cast to 0/false/epoch silently
            vals_l = np.asarray(c.values).tolist()
            nulls = np.fromiter((v is None for v in vals_l),
                                dtype=bool, count=len(vals_l))
            if nulls.any():
                ok = ~nulls
                validity = ok if validity is None \
                    else np.asarray(validity) & ok
        vals = _cast_values(c.values, c.data_type, self.return_type)
        return Column(self.return_type, vals, validity)

    def __repr__(self):
        return f"cast({self.child!r} as {self.return_type.value})"


# ---------------------------------------------------------------------------
# function registry (sig/ analog, without the proc-macro machinery)

_FUNCTIONS: Dict[str, Callable] = {}


def register_function(name: str):
    def deco(fn):
        _FUNCTIONS[name] = fn
        return fn
    return deco


class FuncCall(Expression):
    """Named scalar function over evaluated child columns."""

    def __init__(self, name: str, args: Sequence[Expression],
                 return_type: DataType):
        assert name in _FUNCTIONS, f"unknown function {name}"
        self.name = name
        self.args = list(args)
        self.return_type = return_type

    def eval(self, chunk: DataChunk) -> Column:
        cols = [a.eval(chunk) for a in self.args]
        out = _FUNCTIONS[self.name](self.return_type, *cols)
        assert isinstance(out, Column)
        return out

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


def _window_usecs(window: Column):
    """Interval-literal column → scalar µs, or None for a NULL literal."""
    if window.data_type != DataType.INTERVAL:
        return window.values
    iv = next((v for v in np.asarray(window.values) if v is not None), None)
    return None if iv is None else np.int64(iv.exact_usecs())


@register_function("tumble_start")
def _tumble_start(rt: DataType, ts: Column, window: Column) -> Column:
    """Window start for TUMBLE(ts, interval): ts - ts % window_usecs.

    Reference: the TUMBLE rewrite in the frontend planner; the window size
    must be a month-free interval literal. A NULL window yields NULL.
    """
    w = _window_usecs(window)
    xp = get_xp(ts.values)
    if w is None:
        return Column(rt, xp.zeros_like(ts.values),
                      xp.zeros(ts.values.shape[0], dtype=bool))
    out = ts.values - (ts.values % w)
    return Column(rt, out, ts.validity)


@register_function("tumble_end")
def _tumble_end(rt: DataType, ts: Column, window: Column) -> Column:
    w = _window_usecs(window)
    xp = get_xp(ts.values)
    if w is None:
        return Column(rt, xp.zeros_like(ts.values),
                      xp.zeros(ts.values.shape[0], dtype=bool))
    out = ts.values - (ts.values % w) + w
    return Column(rt, out, ts.validity)


@register_function("extract_epoch")
def _extract_epoch(rt: DataType, ts: Column) -> Column:
    """EXTRACT(EPOCH FROM ts): µs timestamp → seconds (DECIMAL).

    Divide BEFORE applying the decimal scale: multiply-first overflows
    int64 for any modern timestamp (µs × 10^4 > 2^63)."""
    xp = get_xp(ts.values)
    whole = ts.values // xp.int64(1_000_000)
    frac_us = ts.values % xp.int64(1_000_000)
    secs = (whole * xp.int64(DECIMAL_SCALE)
            + frac_us * xp.int64(DECIMAL_SCALE) // xp.int64(1_000_000))
    return Column(rt, secs, ts.validity)


def tumble_start(ts: Expression, window: Interval) -> FuncCall:
    return FuncCall("tumble_start", [ts, Literal(window, DataType.INTERVAL)],
                    ts.return_type)


def tumble_end(ts: Expression, window: Interval) -> FuncCall:
    return FuncCall("tumble_end", [ts, Literal(window, DataType.INTERVAL)],
                    ts.return_type)


class Case(Expression):
    """CASE WHEN …: branchless select over evaluated branches."""

    def __init__(self, whens: Sequence[tuple], else_: Expression):
        # whens: [(cond_expr, value_expr)]
        self.whens = list(whens)
        self.else_ = else_
        self.return_type = else_.return_type
        for _, v in self.whens:
            assert v.return_type == self.return_type

    def eval(self, chunk: DataChunk) -> Column:
        out = self.else_.eval(chunk)
        vals, validity = out.values, out.validity
        cap = chunk.capacity
        xp = get_xp(chunk.visibility, vals)
        taken = xp.zeros(cap, dtype=bool)
        for cond, value in self.whens:
            cc = cond.eval(chunk)
            cv = cc.values & (cc.validity if cc.validity is not None
                              else xp.ones(cap, dtype=bool)) & ~taken
            vc = value.eval(chunk)
            vals = xp.where(cv, vc.values, vals)
            if validity is not None or vc.validity is not None:
                lval = validity if validity is not None \
                    else xp.ones(cap, dtype=bool)
                rval = vc.validity if vc.validity is not None \
                    else xp.ones(cap, dtype=bool)
                validity = xp.where(cv, rval, lval)
            taken = taken | cv
        return Column(self.return_type, vals, validity)

    def __repr__(self):
        return f"case({self.whens!r}, else={self.else_!r})"


# -- scalar function library (vector_op/ analog, host-typed) ---------------
# VARCHAR columns are host object arrays; these run vectorized python
# passes (they are projection-side, not kernel-side). TIMESTAMP is µs
# since epoch (int64, device). NULL in → NULL out, elementwise.

def _host_unary(rt, col, fn):
    vals = np.asarray(col.values)
    ok = np.ones(len(vals), dtype=bool) if col.validity is None \
        else np.asarray(col.validity).copy()
    out = np.empty(len(vals), dtype=object)
    for i in np.flatnonzero(ok):
        v = vals[i]
        if v is None:
            ok[i] = False
            continue
        out[i] = fn(v)
    return Column(rt, out, None if ok.all() else ok)


def _scalar_of(col: Column):
    """First non-null value of a (literal) column, or None."""
    vals = np.asarray(col.values)
    if col.validity is not None:
        idx = np.flatnonzero(np.asarray(col.validity))
        return vals[idx[0]] if len(idx) else None
    return vals[0] if len(vals) else None


@register_function("lower")
def _fn_lower(rt, s: Column) -> Column:
    return _host_unary(rt, s, lambda v: str(v).lower())


@register_function("upper")
def _fn_upper(rt, s: Column) -> Column:
    return _host_unary(rt, s, lambda v: str(v).upper())


@register_function("char_length")
def _fn_char_length(rt, s: Column) -> Column:
    vals = np.asarray(s.values)
    ok = np.ones(len(vals), dtype=bool) if s.validity is None \
        else np.asarray(s.validity).copy()
    out = np.zeros(len(vals), dtype=np.int64)
    for i in np.flatnonzero(ok):
        if vals[i] is None:
            ok[i] = False
        else:
            out[i] = len(str(vals[i]))
    return Column(rt, out, None if ok.all() else ok)


_FUNCTIONS["length"] = _FUNCTIONS["char_length"]   # pg alias


@register_function("substr")
def _fn_substr(rt, s: Column, start: Column, *ln: Column) -> Column:
    st = _scalar_of(start)
    n = _scalar_of(ln[0]) if ln else None
    if st is None:
        return _host_unary(rt, s, lambda v: None)
    # pg window semantics: the window is [start, start+len) in 1-based
    # positions BEFORE clamping — substr('hello', 0, 3) = 'he'
    raw_lo = int(st) - 1
    hi = None if n is None else raw_lo + max(int(n), 0)
    lo = max(raw_lo, 0)
    if hi is not None and hi <= lo:
        return _host_unary(rt, s, lambda v: "")
    return _host_unary(rt, s, lambda v: str(v)[lo:hi])


@register_function("split_part")
def _fn_split_part(rt, s: Column, delim: Column, idx: Column) -> Column:
    d, k = _scalar_of(delim), _scalar_of(idx)
    if d is None or k is None or str(d) == "":
        return _host_unary(rt, s, lambda v: None)
    k = int(k)
    if k == 0:
        raise ValueError("split_part position must not be zero")

    def part(v):
        parts = str(v).split(str(d))
        i = k - 1 if k > 0 else len(parts) + k   # negative: from end
        return parts[i] if 0 <= i < len(parts) else ""
    return _host_unary(rt, s, part)


@register_function("replace")
def _fn_replace(rt, s: Column, old: Column, new: Column) -> Column:
    o, n = _scalar_of(old), _scalar_of(new)
    if o is None or n is None:
        return _host_unary(rt, s, lambda v: None)
    return _host_unary(rt, s, lambda v: str(v).replace(str(o), str(n)))


@register_function("concat")
def _fn_concat(rt, *cols: Column) -> Column:
    n = max(len(np.asarray(c.values)) for c in cols)
    out = np.empty(n, dtype=object)
    for i in range(n):
        parts = []
        for c in cols:
            vals = np.asarray(c.values)
            okc = c.validity
            if okc is not None and not np.asarray(okc)[i]:
                continue                 # pg concat skips NULLs
            v = vals[i]
            if v is not None:
                parts.append(str(v))
        out[i] = "".join(parts)
    return Column(rt, out, None)


# to_char format → strftime (the subset the nexmark corpus uses; the
# reference's to_char lives in expr/src/vector_op/to_char.rs)
_TO_CHAR_MAP = [("YYYY", "%Y"), ("MM", "%m"), ("DD", "%d"),
                ("HH24", "%H"), ("MI", "%M"), ("SS", "%S")]


@register_function("to_char")
def _fn_to_char(rt, ts: Column, fmt: Column) -> Column:
    import datetime
    f = _scalar_of(fmt)
    if f is None:
        return _host_unary(rt, ts, lambda v: None)
    sf = str(f)
    for a, b in _TO_CHAR_MAP:
        sf = sf.replace(a, b)
    epoch = datetime.datetime(1970, 1, 1,
                              tzinfo=datetime.timezone.utc)

    def conv(v):
        return (epoch + datetime.timedelta(
            microseconds=int(v))).strftime(sf)
    return _host_unary(rt, ts, conv)


_DATE_PART_DIV = {
    "second": (1_000_000, 60), "minute": (60_000_000, 60),
    "hour": (3_600_000_000, 24),
}


@register_function("date_part")
def _fn_date_part(rt, field: Column, ts: Column) -> Column:
    import datetime
    f = _scalar_of(field)
    f = str(f).lower() if f is not None else ""
    vals = np.asarray(ts.values)
    ok = np.ones(len(vals), dtype=bool) if ts.validity is None \
        else np.asarray(ts.validity)
    if f in _DATE_PART_DIV:
        div, mod = _DATE_PART_DIV[f]
        out = (vals.astype(np.int64) // div) % mod
        return Column(rt, out.astype(np.int64),
                      None if ok.all() else np.asarray(ok))
    epoch = datetime.datetime(1970, 1, 1,
                              tzinfo=datetime.timezone.utc)
    attr = {"year": "year", "month": "month", "day": "day"}.get(f)
    if attr is None:
        raise ValueError(f"date_part field {f!r} unsupported")
    out = np.zeros(len(vals), dtype=np.int64)
    for i in np.flatnonzero(ok):
        out[i] = getattr(epoch + datetime.timedelta(
            microseconds=int(vals[i])), attr)
    return Column(rt, out, None if ok.all() else np.asarray(ok))


_TRUNC_US = {"second": 1_000_000, "minute": 60_000_000,
             "hour": 3_600_000_000, "day": 86_400_000_000}


@register_function("date_trunc")
def _fn_date_trunc(rt, field: Column, ts: Column) -> Column:
    f = _scalar_of(field)
    f = str(f).lower() if f is not None else ""
    unit = _TRUNC_US.get(f)
    if unit is None:
        raise ValueError(f"date_trunc field {f!r} unsupported")
    vals = np.asarray(ts.values).astype(np.int64)
    out = vals - vals % unit
    ok = ts.validity
    return Column(rt, out, None if ok is None else np.asarray(ok))
