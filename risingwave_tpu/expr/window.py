"""Window-function framework: kinds, calls, vectorized evaluation.

Reference parity: src/expr/src/window_function/{kind.rs:24,call.rs}
(WindowFuncKind: RowNumber/Rank/DenseRank/Lag/Lead/Aggregate) and the
per-partition window states of window_function/state/. TPU re-design:
the reference maintains one incremental WindowState per function and
steps it row by row; here a partition's outputs are recomputed as
whole-column numpy passes (cumsum / accumulate / shift) — the same
"vectorize the partition, don't walk it" stance as the rest of the
build, with O(partition) cost bounded by the delta-driven recompute
ranges in the executor.

Frame semantics (v1): the PostgreSQL DEFAULT frame — RANGE BETWEEN
UNBOUNDED PRECEDING AND CURRENT ROW, which includes the current row's
PEERS (rows equal under ORDER BY). Explicit frame clauses are not
parsed yet and raise at bind time.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.types import DataType


class WindowFuncKind(enum.Enum):
    ROW_NUMBER = "row_number"
    RANK = "rank"
    DENSE_RANK = "dense_rank"
    LAG = "lag"
    LEAD = "lead"
    SUM = "sum"
    COUNT = "count"
    MIN = "min"
    MAX = "max"
    FIRST_VALUE = "first_value"
    LAST_VALUE = "last_value"

    @property
    def needs_input(self) -> bool:
        return self not in (WindowFuncKind.ROW_NUMBER,
                            WindowFuncKind.RANK,
                            WindowFuncKind.DENSE_RANK)


RANK_KINDS = (WindowFuncKind.ROW_NUMBER, WindowFuncKind.RANK,
              WindowFuncKind.DENSE_RANK)


@dataclass(frozen=True)
class WindowCall:
    """One window function over the executor's shared (partition,
    order) window. input_idx indexes the INPUT schema; offset is the
    lag/lead distance."""

    kind: WindowFuncKind
    input_idx: Optional[int] = None
    offset: int = 1

    def output_type(self, input_schema) -> DataType:
        if self.kind in RANK_KINDS or self.kind == WindowFuncKind.COUNT:
            return DataType.INT64
        dt = input_schema[self.input_idx].data_type
        if self.kind == WindowFuncKind.SUM:
            return DataType.INT64 if dt in (
                DataType.INT16, DataType.INT32, DataType.INT64,
                DataType.SERIAL) else dt
        return dt


def _peer_group_bounds(eq_prev: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """(group_start[i], group_end_exclusive[i]) per row, given
    eq_prev[i] = row i has the same ORDER BY key as row i-1."""
    n = len(eq_prev)
    idx = np.arange(n, dtype=np.int64)
    start = np.maximum.accumulate(np.where(eq_prev, 0, idx))
    # end: reverse trick — last index of each group + 1
    is_last = np.ones(n, dtype=bool)
    is_last[:-1] = ~eq_prev[1:]
    end = idx + 1
    # propagate each group-last's end backwards
    rev_end = np.minimum.accumulate(
        np.where(is_last, end, n + 1)[::-1])[::-1]
    return start, rev_end


def compute_window_outputs(
        calls: Sequence[WindowCall],
        n: int,
        eq_prev: np.ndarray,
        inputs: Sequence[Optional[Tuple[np.ndarray, np.ndarray]]],
) -> List[Tuple[np.ndarray, np.ndarray]]:
    """Outputs for one partition, rows already in window order.

    eq_prev[i]: row i is an ORDER BY peer of row i-1 (False at 0).
    inputs[j]: (values, nonnull) arrays for call j, or None.
    Returns per call (values, nonnull) of length n.
    """
    if n == 0:
        return [(np.zeros(0), np.zeros(0, dtype=bool)) for _ in calls]
    start, end = _peer_group_bounds(np.asarray(eq_prev, dtype=bool))
    idx = np.arange(n, dtype=np.int64)
    out: List[Tuple[np.ndarray, np.ndarray]] = []
    for call, inp in zip(calls, inputs):
        k = call.kind
        if k == WindowFuncKind.ROW_NUMBER:
            out.append((idx + 1, np.ones(n, dtype=bool)))
            continue
        if k == WindowFuncKind.RANK:
            out.append((start + 1, np.ones(n, dtype=bool)))
            continue
        if k == WindowFuncKind.DENSE_RANK:
            gid = np.cumsum(~np.asarray(eq_prev, dtype=bool))
            out.append((gid.astype(np.int64),
                        np.ones(n, dtype=bool)))
            continue
        if k == WindowFuncKind.COUNT and inp is None:
            # count(*): every frame row counts
            out.append((end.astype(np.int64), np.ones(n, dtype=bool)))
            continue
        vals, ok = inp
        if k in (WindowFuncKind.LAG, WindowFuncKind.LEAD):
            d = call.offset if k == WindowFuncKind.LAG else -call.offset
            shifted = np.empty_like(vals)
            sok = np.zeros(n, dtype=bool)
            if k == WindowFuncKind.LAG:
                if d < n:
                    shifted[d:] = vals[:n - d]
                    sok[d:] = ok[:n - d]
            else:
                o = call.offset
                if o < n:
                    shifted[:n - o] = vals[o:]
                    sok[:n - o] = ok[o:]
            out.append((shifted, sok))
            continue
        # default-frame aggregates: cumulative through the END of the
        # current row's peer group (pg RANGE ... CURRENT ROW semantics)
        at = end - 1
        if k == WindowFuncKind.COUNT:
            cum = np.cumsum(ok.astype(np.int64))
            out.append((cum[at], np.ones(n, dtype=bool)))
        elif k == WindowFuncKind.SUM:
            cum = np.cumsum(np.where(ok, vals, 0))
            nn = np.cumsum(ok.astype(np.int64))[at] > 0
            out.append((cum[at], nn))
        elif k in (WindowFuncKind.MIN, WindowFuncKind.MAX):
            if np.issubdtype(vals.dtype, np.floating):
                fill = np.inf if k == WindowFuncKind.MIN else -np.inf
            else:
                info = np.iinfo(vals.dtype if
                                np.issubdtype(vals.dtype, np.integer)
                                else np.int64)
                fill = info.max if k == WindowFuncKind.MIN else info.min
            filled = np.where(ok, vals, fill)
            acc = (np.minimum if k == WindowFuncKind.MIN
                   else np.maximum).accumulate(filled)
            nn = np.cumsum(ok.astype(np.int64))[at] > 0
            out.append((acc[at], nn))
        elif k == WindowFuncKind.FIRST_VALUE:
            out.append((np.broadcast_to(vals[0], (n,)).copy(),
                        np.broadcast_to(ok[0], (n,)).copy()))
        elif k == WindowFuncKind.LAST_VALUE:
            out.append((vals[at], ok[at]))
        else:                                    # pragma: no cover
            raise NotImplementedError(k)
    return out
