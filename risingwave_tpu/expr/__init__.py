"""Expression evaluation: vectorized ``eval(DataChunk) -> Column``.

Reference parity: src/expr/src/expr/mod.rs:74,91 (Expression trait) and the
vector_op scalar kernels. TPU re-design: every expression evaluates over the
whole fixed-capacity chunk in one VPU pass (padding rows included — callers
gate with visibility); null validity is a parallel bool array; DECIMAL
arithmetic is exact scaled-int64 fixed point.
"""

from risingwave_tpu.expr.expr import (
    BinaryOp,
    Case,
    Expression,
    FuncCall,
    InputRef,
    Literal,
    UnaryOp,
    and_,
    col,
    lit,
    or_,
    register_function,
    tumble_end,
    tumble_start,
)

__all__ = [
    "Expression",
    "InputRef",
    "Literal",
    "BinaryOp",
    "UnaryOp",
    "FuncCall",
    "Case",
    "col",
    "lit",
    "and_",
    "or_",
    "register_function",
    "tumble_start",
    "tumble_end",
]
