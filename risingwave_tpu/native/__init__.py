"""Loader for the native (C++) runtime kernels.

Compiles native/rw_native.cpp with g++ on first use (cached as a .so
next to the source) and exposes ctypes wrappers. Every entry point has
a pure-Python fallback in risingwave_tpu/storage/sst.py — `lib()`
returns None when no toolchain is available and callers fall back
transparently; outputs are byte-identical either way (tested).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native",
    "rw_native.cpp")
_SO = os.path.join(os.path.dirname(_SRC), "librw_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile() -> bool:
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (pure-Python fallback)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("RW_TPU_DISABLE_NATIVE"):
            return None
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                if not _compile():
                    return None
            l = ctypes.CDLL(_SO)
        except OSError:
            return None
        l.rw_block_encode.restype = ctypes.c_long
        l.rw_block_encode.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_long]
        l.rw_block_decode.restype = ctypes.c_long
        l.rw_block_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_long,
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p, ctypes.c_long,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_long]
        l.rw_bloom_build.restype = None
        l.rw_bloom_build.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_long]
        l.rw_bloom_may_contain.restype = ctypes.c_int32
        l.rw_bloom_may_contain.argtypes = [
            ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_long, ctypes.c_int32]
        _lib = l
        return _lib
