"""StorageTable: batch-side snapshot reads of a materialized table.

Reference parity: src/storage/src/table/batch_table/storage_table.rs:55
— point get + range scan over the committed state at a fixed epoch,
with pk decode. The streaming side writes through StateTable; this is
the read-only view batch queries use (same key codec, no memtable).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.common.chunk import Column, DataChunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.state.keycodec import (
    decode_memcomparable, encode_memcomparable, encode_vnode_prefix,
)
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import StateStore


class StorageTable:
    """Read-only snapshot view over one table id in the state store."""

    def __init__(self, table_id: int, schema: Schema,
                 pk_indices: Sequence[int], store: StateStore,
                 dist_key_indices: Optional[Sequence[int]] = None):
        self.table_id = table_id
        self.schema = schema
        self.pk_indices = list(pk_indices)
        self.store = store
        # reuse StateTable's key codec for gets (no memtable writes)
        self._keys = StateTable(table_id, schema, pk_indices, store,
                                dist_key_indices=dist_key_indices)

    @staticmethod
    def of(state_table: StateTable) -> "StorageTable":
        return StorageTable(state_table.table_id, state_table.schema,
                            state_table.pk_indices, state_table.store,
                            state_table.dist_key_indices)

    def get_row(self, pk_values: Sequence, epoch: int) -> Optional[tuple]:
        key = self._keys._encode_pk(tuple(pk_values))
        return self.store.get(self.table_id, key, epoch)

    def iter_rows(self, epoch: int) -> Iterator[tuple]:
        for _key, row in self.store.iter(self.table_id, epoch):
            yield row

    def scan_chunks(self, epoch: int, chunk_size: int = 1024
                    ) -> Iterator[DataChunk]:
        """Snapshot scan → DataChunks (vectorized column building)."""
        buf: List[tuple] = []
        for row in self.iter_rows(epoch):
            buf.append(row)
            if len(buf) >= chunk_size:
                yield rows_to_chunk(self.schema, buf)
                buf = []
        if buf:
            yield rows_to_chunk(self.schema, buf)


def rows_to_chunk(schema: Schema, rows: List[tuple]) -> DataChunk:
    """Row tuples → one DataChunk (host columns).

    DECIMAL cells accept BOTH value domains, distinguished by type:
    physical scaled int64 (state rows, the storage scan path) passes
    through; logical ``decimal.Decimal`` (``to_pylist`` output — the
    batch agg/join/order executors round-trip rows through it) is
    scaled here. Without this, a logical Decimal stuffed into the
    int64 physical array silently truncates to its integer part and
    then renders divided by the scale."""
    import decimal as _decimal

    from risingwave_tpu.common import types as _types

    n = len(rows)
    from risingwave_tpu.common.chunk import next_pow2
    cap = next_pow2(max(n, 1))
    cols: List[Column] = []
    for i, f in enumerate(schema):
        vals = [r[i] for r in rows]
        dt = f.data_type
        if dt == DataType.DECIMAL and any(
                isinstance(v, _decimal.Decimal) for v in vals):
            vals = [_types.decimal_to_scaled(v)
                    if isinstance(v, _decimal.Decimal) else v
                    for v in vals]
        ok = np.ones(cap, dtype=bool)
        has_null = any(v is None for v in vals)
        if dt.is_device:
            arr = np.zeros(cap, dtype=dt.np_dtype)
            if has_null:
                ok[:n] = [v is not None for v in vals]
                arr[:n] = [0 if v is None else v for v in vals]
            else:
                arr[:n] = vals
        else:
            arr = np.empty(cap, dtype=object)
            arr[:n] = vals
            if has_null:
                ok[:n] = [v is not None for v in vals]
        cols.append(Column(dt, arr, ok if has_null else None))
    vis = np.zeros(cap, dtype=bool)
    vis[:n] = True
    return DataChunk(schema, cols, vis)
