"""Batch engine: ad-hoc queries over committed snapshots.

Reference parity: src/batch/ (~20K LoC) — the pull-based batch
`Executor` tree (src/batch/src/executor/mod.rs:92) that serves
`SELECT` over StorageTable snapshots at the committed epoch. Here the
executor set is host-vectorized numpy over the same DataChunk type the
streaming side uses; the heavy relational ops can promote to the
device kernels when inputs are large (same ops/ layer).
"""

from risingwave_tpu.batch.storage_table import StorageTable
from risingwave_tpu.batch.executors import (
    BatchExecutor, BatchFilter, BatchHashAgg, BatchHashJoin, BatchLimit,
    BatchOrderBy, BatchProject, BatchValues, RowSeqScan, collect,
)

__all__ = [
    "StorageTable", "BatchExecutor", "RowSeqScan", "BatchFilter",
    "BatchProject", "BatchHashAgg", "BatchHashJoin", "BatchOrderBy",
    "BatchLimit", "BatchValues", "collect",
]
