"""Batch executors: pull-based DataChunk iterators.

Reference parity: src/batch/src/executor/ — RowSeqScan
(row_seq_scan.rs), Filter, Project, HashAgg (hash_agg.rs), HashJoin
(join/hash_join.rs, inner), OrderBy/TopN (order_by.rs, top_n.rs),
Limit, Values. Host-vectorized numpy over the shared DataChunk; the
stateful streaming kernels stay the device path (batch queries here
serve MV verification and the local "SELECT" fast path,
scheduler/local.rs analog).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from risingwave_tpu.batch.storage_table import StorageTable, rows_to_chunk
from risingwave_tpu.common.chunk import DataChunk
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.expr import Expression
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.stream.executors.hash_agg import AggCall


class BatchExecutor:
    """Pull-based executor (batch/executor/mod.rs:92 analog)."""

    schema: Schema

    def execute(self) -> Iterator[DataChunk]:
        raise NotImplementedError


def collect(ex: BatchExecutor) -> List[tuple]:
    """Drain an executor into visible row tuples."""
    out: List[tuple] = []
    for chunk in ex.execute():
        out.extend(chunk.to_pylist())
    return out


class BatchValues(BatchExecutor):
    def __init__(self, schema: Schema, rows: List[tuple]):
        self.schema = schema
        self.rows = rows

    def execute(self) -> Iterator[DataChunk]:
        if self.rows:
            yield rows_to_chunk(self.schema, self.rows)


class RowSeqScan(BatchExecutor):
    """Full scan of a storage table at a snapshot epoch."""

    def __init__(self, table: StorageTable, epoch: int,
                 chunk_size: int = 1024):
        self.table = table
        self.schema = table.schema
        self.epoch = epoch
        self.chunk_size = chunk_size

    def execute(self) -> Iterator[DataChunk]:
        yield from self.table.scan_chunks(self.epoch, self.chunk_size)


class BatchFilter(BatchExecutor):
    def __init__(self, child: BatchExecutor, predicate: Expression):
        self.child = child
        self.schema = child.schema
        self.predicate = predicate

    def execute(self) -> Iterator[DataChunk]:
        for chunk in self.child.execute():
            col = self.predicate.eval(chunk)
            keep = np.asarray(col.values).astype(bool)
            if col.validity is not None:
                keep &= np.asarray(col.validity)   # NULL ⇒ drop
            out = chunk.mask(np.asarray(keep))
            if out.cardinality():
                yield out


class BatchProject(BatchExecutor):
    def __init__(self, child: BatchExecutor, exprs: Sequence[Expression],
                 names: Optional[Sequence[str]] = None):
        self.child = child
        self.exprs = list(exprs)
        cols = [e.eval(DataChunk.empty(child.schema)) for e in self.exprs]
        self.schema = Schema([
            Field(names[i] if names else f"col{i}", c.data_type)
            for i, c in enumerate(cols)])

    def execute(self) -> Iterator[DataChunk]:
        for chunk in self.child.execute():
            cols = [e.eval(chunk) for e in self.exprs]
            yield DataChunk(self.schema, cols, chunk.visibility)


class BatchHashAgg(BatchExecutor):
    """Blocking hash aggregation (batch/executor/hash_agg.rs analog).

    Host dict-based v1 — batch group counts are bounded by the MV size;
    the device kernel remains the streaming path.
    """

    def __init__(self, child: BatchExecutor, group_indices: Sequence[int],
                 agg_calls: Sequence[AggCall],
                 names: Optional[Sequence[str]] = None):
        from risingwave_tpu.stream.executors.hash_agg import (
            agg_output_schema,
        )
        self.child = child
        self.group_indices = list(group_indices)
        self.agg_calls = list(agg_calls)
        self.schema = agg_output_schema(child.schema, group_indices,
                                        agg_calls, names)

    def execute(self) -> Iterator[DataChunk]:
        groups: Dict[tuple, List] = {}
        seen: Dict[tuple, set] = {}      # DISTINCT dedup per (group, call)
        for chunk in self.child.execute():
            for row in chunk.to_pylist():
                gk = tuple(row[i] for i in self.group_indices)
                accs = groups.get(gk)
                if accs is None:
                    accs = groups[gk] = [None] * len(self.agg_calls)
                for j, call in enumerate(self.agg_calls):
                    v = None if call.input_idx is None \
                        else row[call.input_idx]
                    if call.distinct and v is not None:
                        s = seen.setdefault((gk, j), set())
                        if v in s:
                            continue
                        s.add(v)
                    accs[j] = _agg_step(call.kind, accs[j], v,
                                        call.input_idx is None)
        rows = []
        for gk, accs in groups.items():
            out = []
            for call, a in zip(self.agg_calls, accs):
                if call.kind == AggKind.COUNT:
                    out.append(a or 0)
                elif call.kind == AggKind.APPROX_COUNT_DISTINCT:
                    out.append(len(a) if isinstance(a, set) else 0)
                else:
                    out.append(a)
            rows.append(gk + tuple(out))
        if rows:
            yield rows_to_chunk(self.schema, rows)


def _agg_step(kind: AggKind, acc, v, count_star: bool):
    if kind == AggKind.COUNT:
        if count_star or v is not None:
            return (acc or 0) + 1
        return acc
    if kind == AggKind.APPROX_COUNT_DISTINCT:
        # batch scans are bounded: the exact distinct count is cheap
        # and strictly dominates the streaming sketch's estimate
        if v is None:
            return acc
        s = acc if isinstance(acc, set) else set()
        s.add(v)
        return s
    if v is None:
        return acc
    if acc is None:
        return v
    if kind == AggKind.SUM:
        return acc + v
    if kind == AggKind.MIN:
        return min(acc, v)
    if kind == AggKind.MAX:
        return max(acc, v)
    raise ValueError(kind)


class BatchHashJoin(BatchExecutor):
    """Inner equi-join: build right, probe left (hash_join.rs analog)."""

    def __init__(self, left: BatchExecutor, right: BatchExecutor,
                 left_keys: Sequence[int], right_keys: Sequence[int]):
        self.left, self.right = left, right
        self.left_keys = list(left_keys)
        self.right_keys = list(right_keys)
        self.schema = Schema(list(left.schema) + list(right.schema))

    def execute(self) -> Iterator[DataChunk]:
        build: Dict[tuple, List[tuple]] = {}
        for chunk in self.right.execute():
            for row in chunk.to_pylist():
                k = tuple(row[i] for i in self.right_keys)
                if any(v is None for v in k):
                    continue
                build.setdefault(k, []).append(row)
        out: List[tuple] = []
        for chunk in self.left.execute():
            for row in chunk.to_pylist():
                k = tuple(row[i] for i in self.left_keys)
                if any(v is None for v in k):
                    continue
                for rrow in build.get(k, ()):
                    out.append(row + rrow)
            if len(out) >= 4096:
                yield rows_to_chunk(self.schema, out)
                out = []
        if out:
            yield rows_to_chunk(self.schema, out)


class BatchOrderBy(BatchExecutor):
    """Blocking sort. order_cols: [(col_idx, descending)]."""

    def __init__(self, child: BatchExecutor,
                 order_cols: Sequence[Tuple[int, bool]]):
        self.child = child
        self.schema = child.schema
        self.order_cols = list(order_cols)

    def execute(self) -> Iterator[DataChunk]:
        rows = collect(self.child)
        for idx, desc in reversed(self.order_cols):
            # None sorts last ascending / first descending (pg NULLS LAST)
            rows.sort(key=lambda r: ((r[idx] is None), r[idx])
                      if r[idx] is not None else (True, 0),
                      reverse=desc)
        if rows:
            yield rows_to_chunk(self.schema, rows)


class BatchLimit(BatchExecutor):
    def __init__(self, child: BatchExecutor, limit: int, offset: int = 0):
        self.child = child
        self.schema = child.schema
        self.limit = limit
        self.offset = offset

    def execute(self) -> Iterator[DataChunk]:
        skip = self.offset
        left = self.limit
        for chunk in self.child.execute():
            rows = chunk.to_pylist()
            if skip:
                take = rows[skip:]
                skip = max(0, skip - len(rows))
                rows = take
            if not rows:
                continue
            if left <= 0:
                return
            rows = rows[:left]
            left -= len(rows)
            if rows:
                yield rows_to_chunk(self.schema, rows)
