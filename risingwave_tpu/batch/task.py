"""Batch task manager: staged, partitioned query execution.

Reference parity: src/batch/src/task/ (task_manager.rs, the per-task
execution contexts) and the batch exchange operators
(src/batch/src/executor/generic_exchange.rs + the hash-shuffle the
scheduler inserts between stages). TPU re-design: a STAGE runs N
partition tasks concurrently; between stages an EXCHANGE re-partitions
rows by hash of the distribution keys (the same vnode hash the
streaming dispatch uses, so batch and streaming agree on ownership).
Tasks are asyncio coroutines; stages MATERIALIZE their output before
the exchange runs (no streaming backpressure yet — batch inputs are
committed snapshots, bounded by the MV size). The stage/partition/
exchange protocol shape is what the distributed deployment reuses:
the coordinator's credit TCP exchange carries the same chunks
between processes.

v1 covers the canonical two-stage shape the reference scheduler emits
for aggregations: parallel vnode-range scans → hash exchange on the
group keys → per-partition HashAgg → gather. Arbitrary plans still run
single-task through plan_batch.
"""

from __future__ import annotations

import asyncio
from typing import Iterator, List, Optional, Sequence

import numpy as np

from risingwave_tpu.batch.executors import (
    BatchExecutor, BatchHashAgg,
)
from risingwave_tpu.batch.storage_table import (
    StorageTable, rows_to_chunk,
)
from risingwave_tpu.common.chunk import DataChunk
from risingwave_tpu.common.hash import VNODE_COUNT, vnodes_of_host
from risingwave_tpu.state.keycodec import encode_vnode_prefix


class VnodeRangeScan(BatchExecutor):
    """Scan one vnode range of a table — a leaf partition task's input
    (row_seq_scan with a vnode bitmap in the reference)."""

    def __init__(self, table: StorageTable, epoch: int,
                 vnode_lo: int, vnode_hi: int, chunk_size: int = 1024):
        self.table = table
        self.schema = table.schema
        self.epoch = epoch
        self.lo, self.hi = vnode_lo, vnode_hi
        self.chunk_size = chunk_size

    def execute(self) -> Iterator[DataChunk]:
        start = encode_vnode_prefix(self.lo)
        end = encode_vnode_prefix(self.hi) if self.hi < VNODE_COUNT \
            else None
        # materialize the store scan EAGERLY: the task yields to the
        # event loop between chunks, and a barrier-triggered compaction
        # could vacuum a lazily-held SST mid-scan (bounded by the MV
        # snapshot size, same stance as StateTable._iter_range_raw)
        all_rows = [row for _k, row in self.table.store.iter(
            self.table.table_id, self.epoch, start, end)]
        for at in range(0, len(all_rows), self.chunk_size):
            yield rows_to_chunk(self.schema,
                                all_rows[at:at + self.chunk_size])


class _StageSource(BatchExecutor):
    """Stage input fed by an exchange (generic_exchange.rs source)."""

    def __init__(self, schema, chunks: List[DataChunk]):
        self.schema = schema
        self._chunks = chunks

    def execute(self) -> Iterator[DataChunk]:
        yield from self._chunks


def _hash_partition(chunk: DataChunk, key_indices: Sequence[int],
                    n: int) -> List[List[tuple]]:
    """Rows → n buckets by the vnode hash of the keys — the typed
    lane-building of the streaming dispatch (dispatch.py _route /
    state_table._encode_pks_bulk pattern: branch on the column TYPE,
    hash the numpy arrays directly, NULLs as the zero lane)."""
    rows = chunk.to_pylist()
    if not rows:
        return [[] for _ in range(n)]
    if not key_indices:
        return [list(rows)] + [[] for _ in range(n - 1)]
    vis = np.asarray(chunk.visibility)
    idx = np.flatnonzero(vis)
    lanes = []
    for i in key_indices:
        c = chunk.columns[i]
        vals = np.asarray(c.values)[idx]
        if c.data_type.is_device:
            if c.validity is not None:
                vals = np.where(np.asarray(c.validity)[idx], vals,
                                np.zeros((), dtype=vals.dtype))
            lanes.append(vals)
        else:
            from risingwave_tpu.common.hash import hash_strings_host
            lanes.append(hash_strings_host(
                np.asarray(vals, dtype=object), len(idx)))
    vn = vnodes_of_host(lanes)
    owner = (vn * n // VNODE_COUNT).astype(np.int64)
    out: List[List[tuple]] = [[] for _ in range(n)]
    for row, o in zip(rows, owner.tolist()):
        out[o].append(row)
    return out


class BatchTaskManager:
    """Run staged partitioned batch plans (task_manager.rs analog)."""

    def __init__(self, parallelism: int = 4):
        assert parallelism >= 1
        self.parallelism = parallelism

    async def _run_stage(self, factories) -> List[List[DataChunk]]:
        """Execute one stage's partition tasks concurrently."""
        async def one(factory):
            ex = factory()
            out = []
            for chunk in ex.execute():
                out.append(chunk)
                await asyncio.sleep(0)     # cooperative scheduling
            return out

        return list(await asyncio.gather(*(one(f) for f in factories)))

    async def run_agg(self, table: StorageTable, epoch: int,
                      group_indices: Sequence[int], agg_calls,
                      names: Optional[Sequence[str]] = None
                      ) -> List[tuple]:
        """The two-stage scheduler shape: parallel scan → hash
        exchange on the group keys → per-partition agg → gather.
        Result rows equal the single-task plan exactly (groups never
        span partitions: ownership is a function of the key hash; a
        grouping-free global agg routes to one partition)."""
        n = self.parallelism
        # stage 1: vnode-range scans
        step = (VNODE_COUNT + n - 1) // n
        scans = [
            (lambda lo=lo: VnodeRangeScan(
                table, epoch, lo, min(lo + step, VNODE_COUNT)))
            for lo in range(0, VNODE_COUNT, step)]
        scanned = await self._run_stage(scans)
        # exchange: hash-partition every scanned chunk by group key
        parts: List[List[tuple]] = [[] for _ in range(n)]
        for chunks in scanned:
            for chunk in chunks:
                for o, rows in enumerate(
                        _hash_partition(chunk, group_indices, n)):
                    parts[o].extend(rows)
        # stage 2: per-partition agg over its routed rows
        aggs = [
            (lambda p=p: BatchHashAgg(
                _StageSource(table.schema,
                             [] if not parts[p] else
                             [rows_to_chunk(table.schema, parts[p])]),
                list(group_indices), list(agg_calls), names))
            for p in range(n)]
        agged = await self._run_stage(aggs)
        # gather (exchange to the root, merge-free: disjoint groups)
        out: List[tuple] = []
        for chunks in agged:
            for chunk in chunks:
                out.extend(chunk.to_pylist())
        return out
